"""Bring-Your-Own-Blocks network (reference: timm/models/byobnet.py:1-3180).

One config-driven meta-architecture covering GENet ("GPU-Efficient"), RepVGG,
MobileOne, the `*-ts` experimental ResNet/ResNeXt family (w/ SE/ECA/GC attn),
RegNetZ, and the CLIP-pretrain ResNets (attention-pool heads).

TPU-first design notes:
  * NHWC feature maps end-to-end; convs are HWIO (flax convention) and lower
    straight onto the MXU without layout transposes.
  * Blocks are plain `nnx.Module`s built from the shared layer library
    (`ConvNormAct`, `create_attn`, `DropPath`); attribute names mirror the
    reference so torch checkpoints remap mechanically.
  * RepVGG / MobileOne structural reparameterization is pure array math on
    HWIO kernels (`reparameterize()`), producing a single fused conv for
    inference — no module surgery needed beyond swapping the branch refs.
  * Stochastic elements (DropPath, DropBlock) carry their own nnx RNG streams,
    so a jitted train step stays purely functional.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from flax import nnx

from ..layers import (
    AttentionPool2d, AvgPool2dAA, BatchNormAct2d, ClassifierHead, ConvNormAct,
    DropBlock2d, DropPath, NormMlpClassifierHead, RotAttentionPool2d,
    calculate_drop_path_rates, create_conv2d, get_aa_layer, get_act_fn,
    get_attn, get_norm_act_layer, make_divisible, to_2tuple,
)
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._manipulate import checkpoint_seq
from ._registry import generate_default_cfgs, register_model
from .resnet import avg_pool2d, max_pool2d

__all__ = ['ByobNet', 'ByoModelCfg', 'ByoBlockCfg', 'create_byob_stem', 'create_block']


@dataclass
class ByoBlockCfg:
    """Config for one block (or a stage of repeated blocks) — reference
    byobnet.py:68-86. Field names are kept verbatim for recipe parity."""
    type: Union[str, Callable]
    d: int  # depth (repeats)
    c: int  # out channels
    s: int = 2  # stage stride (first block)
    gs: Optional[Union[int, Callable]] = None  # group-size (1 = depthwise)
    br: float = 1.  # bottleneck ratio

    attn_layer: Optional[str] = None
    attn_kwargs: Optional[Dict[str, Any]] = None
    self_attn_layer: Optional[str] = None
    self_attn_kwargs: Optional[Dict[str, Any]] = None
    block_kwargs: Optional[Dict[str, Any]] = None


@dataclass
class ByoModelCfg:
    """Whole-model config — reference byobnet.py:89-120."""
    blocks: Tuple[Union[ByoBlockCfg, Tuple[ByoBlockCfg, ...]], ...]
    downsample: str = 'conv1x1'
    stem_type: str = '3x3'
    stem_pool: Optional[str] = 'maxpool'
    stem_chs: Union[int, List[int], Tuple[int, ...]] = 32
    width_factor: float = 1.0
    num_features: int = 0  # 0 = no final 1x1 conv
    zero_init_last: bool = True
    fixed_input_size: bool = False

    act_layer: str = 'relu'
    norm_layer: Union[str, Callable] = 'batchnorm'
    aa_layer: str = ''

    head_hidden_size: Optional[int] = None
    head_type: str = 'classifier'

    attn_layer: Optional[str] = None
    attn_kwargs: dict = field(default_factory=dict)
    self_attn_layer: Optional[str] = None
    self_attn_kwargs: dict = field(default_factory=dict)
    block_kwargs: Dict[str, Any] = field(default_factory=dict)


def _rep_vgg_bcfg(d=(4, 6, 16, 1), wf=(1., 1., 1., 1.), groups: int = 0):
    c = (64, 128, 256, 512)
    group_size = 0
    if groups > 0:
        group_size = lambda chs, idx: chs // groups if (idx + 1) % 2 == 0 else 0
    return tuple([ByoBlockCfg(type='rep', d=d_, c=c_ * wf_, gs=group_size)
                  for d_, c_, wf_ in zip(d, c, wf)])


def _mobileone_bcfg(d=(2, 8, 10, 1), wf=(1., 1., 1., 1.), se_blocks=(), num_conv_branches: int = 1):
    c = (64, 128, 256, 512)
    prev_c = min(64, c[0] * wf[0])
    se_blocks = se_blocks or (0,) * len(d)
    bcfg = []
    for d_, c_, w_, se_ in zip(d, c, wf, se_blocks):
        scfg = []
        for i in range(d_):
            out_c = c_ * w_
            bk = dict(num_conv_branches=num_conv_branches)
            ak = {}
            if i >= d_ - se_:
                ak['attn_layer'] = 'se'
            scfg += [ByoBlockCfg(type='one', d=1, c=prev_c, gs=1, block_kwargs=bk, **ak)]
            scfg += [ByoBlockCfg(type='one', d=1, c=out_c, gs=0,
                                 block_kwargs=dict(kernel_size=1, **bk), **ak)]
            prev_c = out_c
        bcfg += [scfg]
    return bcfg


def interleave_blocks(types: Tuple[str, str], d: int, every: Union[int, List[int]] = 1,
                      first: bool = False, **kwargs) -> Tuple[ByoBlockCfg, ...]:
    """Interleave two block types through a stage (reference byobnet.py:179)."""
    assert len(types) == 2
    if isinstance(every, int):
        every = list(range(0 if first else every, d, every + 1))
        if not every:
            every = [d - 1]
    return tuple(ByoBlockCfg(type=types[1] if i in every else types[0], d=1, **kwargs)
                 for i in range(d))


def expand_blocks_cfg(stage_blocks_cfg) -> List[ByoBlockCfg]:
    if not isinstance(stage_blocks_cfg, Sequence):
        stage_blocks_cfg = (stage_blocks_cfg,)
    block_cfgs = []
    for cfg in stage_blocks_cfg:
        block_cfgs += [replace(cfg, d=1) for _ in range(cfg.d)]
    return block_cfgs


def num_groups(group_size, channels):
    if not group_size:  # 0 or None → normal conv
        return 1
    assert channels % group_size == 0
    return channels // group_size


@dataclass
class LayerFn:
    """Bundle of layer factories threaded through block construction
    (reference byobnet.py:247). All factories already have norm/act bound."""
    conv_norm_act: Callable = ConvNormAct
    norm_act: Callable = BatchNormAct2d
    act: Union[str, Callable] = 'relu'
    attn: Optional[Callable] = None
    self_attn: Optional[Callable] = None


class DownsampleAvg(nnx.Module):
    """AvgPool + 1x1 conv shortcut ('D' variants, reference byobnet.py:256)."""

    def __init__(self, in_chs, out_chs, stride=1, dilation=1, apply_act=False,
                 layers: Optional[LayerFn] = None, *, dtype=None, param_dtype=jnp.float32, rngs):
        layers = layers or LayerFn()
        self.pool_stride = stride if dilation == 1 else 1
        self.do_pool = stride > 1 or dilation > 1
        self.conv = layers.conv_norm_act(
            in_chs, out_chs, 1, apply_act=apply_act, dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        if self.do_pool:
            x = avg_pool2d(x, 2, self.pool_stride, pad_same=True)
        return self.conv(x)


def create_shortcut(downsample_type, in_chs, out_chs, stride, dilation, layers, *,
                    dtype=None, param_dtype=jnp.float32, rngs, **kwargs):
    """None = no shortcut; 'identity' sentinel handled by caller via is-None
    checks (reference byobnet.py:306-341)."""
    assert downsample_type in ('avg', 'conv1x1', '')
    if in_chs != out_chs or stride != 1 or dilation[0] != dilation[1]:
        if not downsample_type:
            return None
        if downsample_type == 'avg':
            return DownsampleAvg(in_chs, out_chs, stride=stride, dilation=dilation[0],
                                 layers=layers, dtype=dtype, param_dtype=param_dtype, rngs=rngs, **kwargs)
        return layers.conv_norm_act(
            in_chs, out_chs, 1, stride=stride, dilation=dilation[0],
            dtype=dtype, param_dtype=param_dtype, rngs=rngs, **kwargs)
    return _identity


def _identity(x):
    return x


def _zero_bn_scale(cna):
    """Zero the BN scale of a ConvNormAct, if it has one (zero_init_last)."""
    bn = getattr(cna, 'bn', None)
    if bn is not None and getattr(bn, 'scale', None) is not None:
        bn.scale[...] = jnp.zeros_like(bn.scale[...])


class BasicBlock(nnx.Module):
    """kxk + kxk residual (reference byobnet.py:341)."""

    def __init__(self, in_chs, out_chs, kernel_size=3, stride=1, dilation=(1, 1),
                 group_size=None, bottle_ratio=1.0, downsample='avg', attn_last=True,
                 linear_out=False, layers: Optional[LayerFn] = None, drop_block=None,
                 drop_path_rate=0., *, dtype=None, param_dtype=jnp.float32, rngs):
        layers = layers or LayerFn()
        mid_chs = make_divisible(out_chs * bottle_ratio)
        groups = num_groups(group_size, mid_chs)
        dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        self.shortcut = create_shortcut(
            downsample, in_chs, out_chs, stride=stride, dilation=dilation,
            apply_act=False, layers=layers, **dd)
        self.conv1_kxk = layers.conv_norm_act(
            in_chs, mid_chs, kernel_size, stride=stride, dilation=dilation[0], **dd)
        self.attn = None if attn_last or layers.attn is None else layers.attn(mid_chs, **dd)
        self.conv2_kxk = layers.conv_norm_act(
            mid_chs, out_chs, kernel_size, dilation=dilation[1], groups=groups,
            drop_layer=drop_block, apply_act=False, **dd)
        self.attn_last = None if not attn_last or layers.attn is None else layers.attn(out_chs, **dd)
        self.drop_path = DropPath(drop_path_rate, rngs=rngs)
        self.act = None if linear_out else get_act_fn(layers.act)

    def zero_init_last(self):
        if self.shortcut is not None:
            _zero_bn_scale(self.conv2_kxk)

    def __call__(self, x):
        shortcut = x
        x = self.conv1_kxk(x)
        if self.attn is not None:
            x = self.attn(x)
        x = self.conv2_kxk(x)
        if self.attn_last is not None:
            x = self.attn_last(x)
        x = self.drop_path(x)
        if self.shortcut is not None:
            x = x + self.shortcut(shortcut)
        return self.act(x) if self.act is not None else x


class BottleneckBlock(nnx.Module):
    """1x1 - kxk - 1x1 residual (reference byobnet.py:415)."""

    def __init__(self, in_chs, out_chs, kernel_size=3, stride=1, dilation=(1, 1),
                 bottle_ratio=1., group_size=None, downsample='avg', attn_last=False,
                 linear_out=False, extra_conv=False, bottle_in=False,
                 layers: Optional[LayerFn] = None, drop_block=None, drop_path_rate=0.,
                 *, dtype=None, param_dtype=jnp.float32, rngs):
        layers = layers or LayerFn()
        mid_chs = make_divisible((in_chs if bottle_in else out_chs) * bottle_ratio)
        groups = num_groups(group_size, mid_chs)
        dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        self.shortcut = create_shortcut(
            downsample, in_chs, out_chs, stride=stride, dilation=dilation,
            apply_act=False, layers=layers, **dd)
        self.conv1_1x1 = layers.conv_norm_act(in_chs, mid_chs, 1, **dd)
        self.conv2_kxk = layers.conv_norm_act(
            mid_chs, mid_chs, kernel_size, stride=stride, dilation=dilation[0],
            groups=groups, drop_layer=drop_block, **dd)
        self.conv2b_kxk = layers.conv_norm_act(
            mid_chs, mid_chs, kernel_size, dilation=dilation[1], groups=groups, **dd) \
            if extra_conv else None
        self.attn = None if attn_last or layers.attn is None else layers.attn(mid_chs, **dd)
        self.conv3_1x1 = layers.conv_norm_act(mid_chs, out_chs, 1, apply_act=False, **dd)
        self.attn_last = None if not attn_last or layers.attn is None else layers.attn(out_chs, **dd)
        self.drop_path = DropPath(drop_path_rate, rngs=rngs)
        self.act = None if linear_out else get_act_fn(layers.act)

    def zero_init_last(self):
        if self.shortcut is not None:
            _zero_bn_scale(self.conv3_1x1)

    def __call__(self, x):
        shortcut = x
        x = self.conv1_1x1(x)
        x = self.conv2_kxk(x)
        if self.conv2b_kxk is not None:
            x = self.conv2b_kxk(x)
        if self.attn is not None:
            x = self.attn(x)
        x = self.conv3_1x1(x)
        if self.attn_last is not None:
            x = self.attn_last(x)
        x = self.drop_path(x)
        if self.shortcut is not None:
            x = x + self.shortcut(shortcut)
        return self.act(x) if self.act is not None else x


class DarkBlock(nnx.Module):
    """1x1 + kxk (DarkNet-style) residual (reference byobnet.py:505)."""

    def __init__(self, in_chs, out_chs, kernel_size=3, stride=1, dilation=(1, 1),
                 bottle_ratio=1.0, group_size=None, downsample='avg', attn_last=True,
                 linear_out=False, layers: Optional[LayerFn] = None, drop_block=None,
                 drop_path_rate=0., *, dtype=None, param_dtype=jnp.float32, rngs):
        layers = layers or LayerFn()
        mid_chs = make_divisible(out_chs * bottle_ratio)
        groups = num_groups(group_size, mid_chs)
        dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        self.shortcut = create_shortcut(
            downsample, in_chs, out_chs, stride=stride, dilation=dilation,
            apply_act=False, layers=layers, **dd)
        self.conv1_1x1 = layers.conv_norm_act(in_chs, mid_chs, 1, **dd)
        self.attn = None if attn_last or layers.attn is None else layers.attn(mid_chs, **dd)
        self.conv2_kxk = layers.conv_norm_act(
            mid_chs, out_chs, kernel_size, stride=stride, dilation=dilation[0],
            groups=groups, drop_layer=drop_block, apply_act=False, **dd)
        self.attn_last = None if not attn_last or layers.attn is None else layers.attn(out_chs, **dd)
        self.drop_path = DropPath(drop_path_rate, rngs=rngs)
        self.act = None if linear_out else get_act_fn(layers.act)

    def zero_init_last(self):
        if self.shortcut is not None:
            _zero_bn_scale(self.conv2_kxk)

    def __call__(self, x):
        shortcut = x
        x = self.conv1_1x1(x)
        if self.attn is not None:
            x = self.attn(x)
        x = self.conv2_kxk(x)
        if self.attn_last is not None:
            x = self.attn_last(x)
        x = self.drop_path(x)
        if self.shortcut is not None:
            x = x + self.shortcut(shortcut)
        return self.act(x) if self.act is not None else x


class EdgeBlock(nnx.Module):
    """kxk + 1x1 ('edge residual') block (reference byobnet.py:587)."""

    def __init__(self, in_chs, out_chs, kernel_size=3, stride=1, dilation=(1, 1),
                 bottle_ratio=1.0, group_size=None, downsample='avg', attn_last=False,
                 linear_out=False, layers: Optional[LayerFn] = None, drop_block=None,
                 drop_path_rate=0., *, dtype=None, param_dtype=jnp.float32, rngs):
        layers = layers or LayerFn()
        mid_chs = make_divisible(out_chs * bottle_ratio)
        groups = num_groups(group_size, mid_chs)
        dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        self.shortcut = create_shortcut(
            downsample, in_chs, out_chs, stride=stride, dilation=dilation,
            apply_act=False, layers=layers, **dd)
        self.conv1_kxk = layers.conv_norm_act(
            in_chs, mid_chs, kernel_size, stride=stride, dilation=dilation[0],
            groups=groups, drop_layer=drop_block, **dd)
        self.attn = None if attn_last or layers.attn is None else layers.attn(mid_chs, **dd)
        self.conv2_1x1 = layers.conv_norm_act(mid_chs, out_chs, 1, apply_act=False, **dd)
        self.attn_last = None if not attn_last or layers.attn is None else layers.attn(out_chs, **dd)
        self.drop_path = DropPath(drop_path_rate, rngs=rngs)
        self.act = None if linear_out else get_act_fn(layers.act)

    def zero_init_last(self):
        if self.shortcut is not None:
            _zero_bn_scale(self.conv2_1x1)

    def __call__(self, x):
        shortcut = x
        x = self.conv1_kxk(x)
        if self.attn is not None:
            x = self.attn(x)
        x = self.conv2_1x1(x)
        if self.attn_last is not None:
            x = self.attn_last(x)
        x = self.drop_path(x)
        if self.shortcut is not None:
            x = x + self.shortcut(shortcut)
        return self.act(x) if self.act is not None else x


def _fuse_conv_bn(cna) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fold a ConvNormAct's BN into its HWIO conv kernel → (kernel, bias)."""
    kernel = cna.conv.kernel[...]
    bn = cna.bn
    std = jnp.sqrt(bn.var[...] + bn.epsilon)
    gamma = bn.scale[...] if bn.scale is not None else jnp.ones_like(std)
    beta = bn.bias[...] if bn.bias is not None else jnp.zeros_like(std)
    t = gamma / std  # per out-channel
    return kernel * t[None, None, None, :], beta - bn.mean[...] * t


def _bn_identity_kernel_bias(bn, in_chs, groups, kernel_size) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fold a bare BN (identity branch) into an HWIO conv kernel."""
    kh, kw = to_2tuple(kernel_size)
    input_dim = in_chs // groups
    kernel = jnp.zeros((kh, kw, input_dim, in_chs), jnp.float32)
    idx = jnp.arange(in_chs)
    kernel = kernel.at[kh // 2, kw // 2, idx % input_dim, idx].set(1.0)
    std = jnp.sqrt(bn.var[...] + bn.epsilon)
    gamma = bn.scale[...] if bn.scale is not None else jnp.ones_like(std)
    beta = bn.bias[...] if bn.bias is not None else jnp.zeros_like(std)
    t = gamma / std
    return kernel * t[None, None, None, :], beta - bn.mean[...] * t


def _pad_1x1_to_kxk(kernel_1x1, kernel_size) -> jnp.ndarray:
    kh, kw = to_2tuple(kernel_size)
    ph, pw = kh // 2, kw // 2
    return jnp.pad(kernel_1x1, ((ph, kh - 1 - ph), (pw, kw - 1 - pw), (0, 0), (0, 0)))


def _make_reparam_conv(in_chs, out_chs, kernel_size, stride, dilation, groups, kernel, bias):
    """Build the deploy-mode fused conv holding (kernel, bias)."""
    conv = create_conv2d(
        in_chs, out_chs, kernel_size, stride=stride, padding=None,
        dilation=dilation, groups=groups, bias=True, rngs=nnx.Rngs(0))
    conv.kernel[...] = kernel
    conv.bias[...] = bias
    return conv


class RepVggBlock(nnx.Module):
    """RepVGG block: kxk + 1x1 + identity branches, fusable to one conv
    (reference byobnet.py:666)."""

    def __init__(self, in_chs, out_chs, kernel_size=3, stride=1, dilation=(1, 1),
                 bottle_ratio=1.0, group_size=None, downsample='',
                 layers: Optional[LayerFn] = None, drop_block=None, drop_path_rate=0.,
                 inference_mode=False, *, dtype=None, param_dtype=jnp.float32, rngs):
        self.groups = groups = num_groups(group_size, in_chs)
        self.in_chs, self.out_chs = in_chs, out_chs
        self.kernel_size, self.stride, self.dilation = kernel_size, stride, dilation
        layers = layers or LayerFn()
        dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        self.reparam_conv = nnx.data(None)
        use_ident = in_chs == out_chs and stride == 1 and dilation[0] == dilation[1]
        self.identity = layers.norm_act(out_chs, apply_act=False, **dd) if use_ident else None
        self.conv_kxk = layers.conv_norm_act(
            in_chs, out_chs, kernel_size, stride=stride, dilation=dilation[0],
            groups=groups, drop_layer=drop_block, apply_act=False, **dd)
        self.conv_1x1 = layers.conv_norm_act(
            in_chs, out_chs, 1, stride=stride, groups=groups, apply_act=False, **dd)
        self.drop_path = DropPath(drop_path_rate if use_ident else 0.0, rngs=rngs)
        self.attn = None if layers.attn is None else layers.attn(out_chs, **dd)
        self.act = get_act_fn(layers.act)

    def __call__(self, x):
        if self.reparam_conv is not None:
            x = self.reparam_conv(x)
            if self.attn is not None:
                x = self.attn(x)
            return self.act(x)
        if self.identity is None:
            x = self.conv_1x1(x) + self.conv_kxk(x)
        else:
            identity = self.identity(x)
            x = self.conv_1x1(x) + self.conv_kxk(x)
            x = self.drop_path(x)
            x = x + identity
        if self.attn is not None:
            x = self.attn(x)
        return self.act(x)

    def reparameterize(self):
        if self.reparam_conv is not None:
            return
        kernel, bias = _fuse_conv_bn(self.conv_kxk)
        k1, b1 = _fuse_conv_bn(self.conv_1x1)
        kernel = kernel + _pad_1x1_to_kxk(k1, self.kernel_size)
        bias = bias + b1
        if self.identity is not None:
            ki, bi = _bn_identity_kernel_bias(self.identity, self.in_chs, self.groups, self.kernel_size)
            kernel = kernel + ki
            bias = bias + bi
        self.reparam_conv = nnx.data(_make_reparam_conv(
            self.in_chs, self.out_chs, self.kernel_size, self.stride, self.dilation[0],
            self.groups, kernel, bias))
        self.identity = self.conv_kxk = self.conv_1x1 = None


class MobileOneBlock(nnx.Module):
    """MobileOne over-parameterized block: N kxk branches + 1x1 scale +
    identity, fusable for deploy (reference byobnet.py:848)."""

    def __init__(self, in_chs, out_chs, kernel_size=3, stride=1, dilation=(1, 1),
                 bottle_ratio=1.0, group_size=None, downsample='', inference_mode=False,
                 num_conv_branches=1, layers: Optional[LayerFn] = None, drop_block=None,
                 drop_path_rate=0., *, dtype=None, param_dtype=jnp.float32, rngs):
        self.num_conv_branches = num_conv_branches
        self.groups = groups = num_groups(group_size, in_chs)
        self.in_chs, self.out_chs = in_chs, out_chs
        self.kernel_size, self.stride, self.dilation = kernel_size, stride, dilation
        layers = layers or LayerFn()
        dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        self.reparam_conv = nnx.data(None)
        use_ident = in_chs == out_chs and stride == 1 and dilation[0] == dilation[1]
        self.identity = layers.norm_act(out_chs, apply_act=False, **dd) if use_ident else None
        self.conv_kxk = nnx.List([
            layers.conv_norm_act(
                in_chs, out_chs, kernel_size, stride=stride, groups=groups,
                apply_act=False, **dd)
            for _ in range(num_conv_branches)])
        self.conv_scale = layers.conv_norm_act(
            in_chs, out_chs, 1, stride=stride, groups=groups, apply_act=False, **dd) \
            if kernel_size > 1 else None
        self.drop_path = DropPath(drop_path_rate if use_ident else 0.0, rngs=rngs)
        self.attn = None if layers.attn is None else layers.attn(out_chs, **dd)
        self.act = get_act_fn(layers.act)

    def __call__(self, x):
        if self.reparam_conv is not None:
            out = self.reparam_conv(x)
            if self.attn is not None:
                out = self.attn(out)
            return self.act(out)
        identity_out = self.identity(x) if self.identity is not None else 0
        out = self.conv_scale(x) if self.conv_scale is not None else 0
        for ck in self.conv_kxk:
            out = out + ck(x)
        out = self.drop_path(out)
        out = out + identity_out
        if self.attn is not None:
            out = self.attn(out)
        return self.act(out)

    def reparameterize(self):
        if self.reparam_conv is not None:
            return
        kernel = jnp.zeros(1, jnp.float32)
        bias = jnp.zeros(1, jnp.float32)
        if self.conv_scale is not None:
            ks, bs = _fuse_conv_bn(self.conv_scale)
            kernel = _pad_1x1_to_kxk(ks, self.kernel_size)
            bias = bs
        for ck in self.conv_kxk:
            kc, bc = _fuse_conv_bn(ck)
            kernel = kernel + kc
            bias = bias + bc
        if self.identity is not None:
            ki, bi = _bn_identity_kernel_bias(self.identity, self.in_chs, self.groups, self.kernel_size)
            kernel = kernel + ki
            bias = bias + bi
        self.reparam_conv = nnx.data(_make_reparam_conv(
            self.in_chs, self.out_chs, self.kernel_size, self.stride, self.dilation[0],
            self.groups, kernel, bias))
        self.identity = self.conv_scale = None
        self.conv_kxk = None


class SelfAttnBlock(nnx.Module):
    """1x1 - (kxk) - self-attn - 1x1 residual (reference byobnet.py:1054).
    The self-attn layer comes from the layer bundle (bottleneck/halo/lambda)."""

    def __init__(self, in_chs, out_chs, kernel_size=3, stride=1, dilation=(1, 1),
                 bottle_ratio=1., group_size=None, downsample='avg', extra_conv=False,
                 linear_out=False, bottle_in=False, post_attn_na=True, feat_size=None,
                 layers: Optional[LayerFn] = None, drop_block=None, drop_path_rate=0.,
                 *, dtype=None, param_dtype=jnp.float32, rngs):
        assert layers is not None and layers.self_attn is not None
        mid_chs = make_divisible((in_chs if bottle_in else out_chs) * bottle_ratio)
        groups = num_groups(group_size, mid_chs)
        dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        self.shortcut = create_shortcut(
            downsample, in_chs, out_chs, stride=stride, dilation=dilation,
            apply_act=False, layers=layers, **dd)
        self.conv1_1x1 = layers.conv_norm_act(in_chs, mid_chs, 1, **dd)
        if extra_conv:
            self.conv2_kxk = layers.conv_norm_act(
                mid_chs, mid_chs, kernel_size, stride=stride, dilation=dilation[0],
                groups=groups, drop_layer=drop_block, **dd)
            stride = 1  # striding done by the conv
        else:
            self.conv2_kxk = None
        opt_kwargs = {} if feat_size is None else dict(feat_size=feat_size)
        self.self_attn = layers.self_attn(mid_chs, stride=stride, **opt_kwargs, **dd)
        self.post_attn = layers.norm_act(mid_chs, **dd) if post_attn_na else None
        self.conv3_1x1 = layers.conv_norm_act(mid_chs, out_chs, 1, apply_act=False, **dd)
        self.drop_path = DropPath(drop_path_rate, rngs=rngs)
        self.act = None if linear_out else get_act_fn(layers.act)

    def zero_init_last(self):
        if self.shortcut is not None:
            _zero_bn_scale(self.conv3_1x1)

    def __call__(self, x):
        shortcut = x
        x = self.conv1_1x1(x)
        if self.conv2_kxk is not None:
            x = self.conv2_kxk(x)
        x = self.self_attn(x)
        if self.post_attn is not None:
            x = self.post_attn(x)
        x = self.conv3_1x1(x)
        x = self.drop_path(x)
        if self.shortcut is not None:
            x = x + self.shortcut(shortcut)
        return self.act(x) if self.act is not None else x


_block_registry = dict(
    basic=BasicBlock,
    bottle=BottleneckBlock,
    dark=DarkBlock,
    edge=EdgeBlock,
    rep=RepVggBlock,
    one=MobileOneBlock,
    self_attn=SelfAttnBlock,
)


def register_block(block_type: str, block_fn):
    _block_registry[block_type] = block_fn


def create_block(block: Union[str, Callable], **kwargs):
    if isinstance(block, str):
        block = _block_registry[block]
    return block(**kwargs)


class Stem(nnx.Module):
    """Stacked-conv stem with optional trailing pool (reference byobnet.py:1160).
    Conv attributes are named conv1..convN to mirror the reference module tree."""

    def __init__(self, in_chs, out_chs, kernel_size=3, stride=4, pool='maxpool',
                 num_rep=3, num_act=None, chs_decay=0.5, layers: Optional[LayerFn] = None,
                 *, dtype=None, param_dtype=jnp.float32, rngs):
        assert stride in (2, 4)
        layers = layers or LayerFn()
        if isinstance(out_chs, (list, tuple)):
            num_rep = len(out_chs)
            stem_chs = out_chs
        else:
            stem_chs = [round(out_chs * chs_decay ** i) for i in range(num_rep)][::-1]

        self.stride = stride
        self.feature_info = []
        stem_strides = [2] + [1] * (num_rep - 1)
        if stride == 4 and not pool:
            stem_strides[-1] = 2
        num_act = num_rep if num_act is None else num_act
        stem_norm_acts = [False] * (num_rep - num_act) + [True] * num_act
        prev_chs = in_chs
        curr_stride = 1
        self.num_rep = num_rep
        prev_feat = ''
        self.last_feat_idx = None
        for i, (ch, s, na) in enumerate(zip(stem_chs, stem_strides, stem_norm_acts)):
            dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            if na:
                conv = layers.conv_norm_act(prev_chs, ch, kernel_size, stride=s, **dd)
            else:
                conv = create_conv2d(prev_chs, ch, kernel_size, stride=s, padding=None, **dd)
            if i > 0 and s > 1:
                self.last_feat_idx = i - 1
                self.feature_info.append(dict(num_chs=prev_chs, reduction=curr_stride, module=prev_feat, stage=0))
            setattr(self, f'conv{i + 1}', conv)
            prev_chs = ch
            curr_stride *= s
            prev_feat = f'conv{i + 1}'

        self.pool = (pool or '').lower()
        if self.pool:
            assert self.pool in ('max', 'maxpool', 'avg', 'avgpool', 'max2', 'avg2')
            self.last_feat_idx = num_rep - 1
            self.feature_info.append(dict(num_chs=prev_chs, reduction=curr_stride, module=prev_feat, stage=0))
            curr_stride *= 2
            prev_feat = 'pool'
        self.feature_info.append(dict(num_chs=prev_chs, reduction=curr_stride, module=prev_feat, stage=0))
        assert curr_stride == stride

    def _apply_pool(self, x):
        if not self.pool:
            return x
        if self.pool == 'max2':
            return max_pool2d(x, 2, 2, padding=((0, 0), (0, 0), (0, 0), (0, 0)))
        if self.pool == 'avg2':
            return avg_pool2d(x, 2, 2)
        if 'max' in self.pool:
            return max_pool2d(x, 3, 2)
        return avg_pool2d(x, 3, 2, pad_same=True)  # 'avg'/'avgpool', 3x3/s2

    def __call__(self, x):
        for i in range(self.num_rep):
            x = getattr(self, f'conv{i + 1}')(x)
        return self._apply_pool(x)

    def forward_intermediates(self, x):
        intermediate = None
        for i in range(self.num_rep):
            x = getattr(self, f'conv{i + 1}')(x)
            if self.last_feat_idx is not None and i == self.last_feat_idx:
                intermediate = x
        x = self._apply_pool(x)
        return x, intermediate


def create_byob_stem(in_chs, out_chs, stem_type='', pool_type='', feat_prefix='stem',
                     layers: Optional[LayerFn] = None, *, dtype=None, param_dtype=jnp.float32, rngs):
    layers = layers or LayerFn()
    dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
    assert stem_type in ('', 'quad', 'quad2', 'tiered', 'deep', 'rep', 'one', '7x7', '3x3')
    if 'quad' in stem_type:
        num_act = 2 if 'quad2' in stem_type else None
        stem = Stem(in_chs, out_chs, num_rep=4, num_act=num_act, pool=pool_type, layers=layers, **dd)
    elif 'tiered' in stem_type:
        stem = Stem(in_chs, (3 * out_chs // 8, out_chs // 2, out_chs), pool=pool_type, layers=layers, **dd)
    elif 'deep' in stem_type:
        stem = Stem(in_chs, out_chs, num_rep=3, chs_decay=1.0, pool=pool_type, layers=layers, **dd)
    elif 'rep' in stem_type:
        stem = RepVggBlock(in_chs, out_chs, stride=2, layers=layers, **dd)
    elif 'one' in stem_type:
        stem = MobileOneBlock(in_chs, out_chs, kernel_size=3, stride=2, layers=layers, **dd)
    elif '7x7' in stem_type:
        if pool_type:
            stem = Stem(in_chs, out_chs, 7, num_rep=1, pool=pool_type, layers=layers, **dd)
        else:
            stem = layers.conv_norm_act(in_chs, out_chs, 7, stride=2, **dd)
    else:
        if isinstance(out_chs, (tuple, list)):
            stem = Stem(in_chs, out_chs, 3, pool=pool_type, layers=layers, **dd)
        elif pool_type:
            stem = Stem(in_chs, out_chs, 3, num_rep=1, pool=pool_type, layers=layers, **dd)
        else:
            stem = layers.conv_norm_act(in_chs, out_chs, 3, stride=2, **dd)

    if isinstance(stem, Stem):
        feature_info = [dict(f, module='.'.join([feat_prefix, f['module']])) for f in stem.feature_info]
    else:
        feature_info = [dict(num_chs=out_chs, reduction=2, module=feat_prefix, stage=0)]
    return stem, feature_info


def reduce_feat_size(feat_size, stride=2):
    return None if feat_size is None else tuple([s // stride for s in feat_size])


def override_kwargs(block_kwargs, model_kwargs):
    out_kwargs = block_kwargs if block_kwargs is not None else model_kwargs
    return out_kwargs or {}


def update_block_kwargs(block_kwargs: Dict[str, Any], block_cfg: ByoBlockCfg, model_cfg: ByoModelCfg):
    """Overlay per-block attn/self-attn/extra kwargs onto the stage defaults
    (reference byobnet.py:1307)."""
    layer_fns = block_kwargs['layers']

    attn_set = block_cfg.attn_layer is not None
    if attn_set or block_cfg.attn_kwargs is not None:
        if attn_set and not block_cfg.attn_layer:
            attn_layer = None
        else:
            attn_kwargs = override_kwargs(block_cfg.attn_kwargs, model_cfg.attn_kwargs)
            attn_layer = block_cfg.attn_layer or model_cfg.attn_layer
            attn_layer = partial(get_attn(attn_layer), **attn_kwargs) if attn_layer is not None else None
        layer_fns = replace(layer_fns, attn=attn_layer)

    self_attn_set = block_cfg.self_attn_layer is not None
    if self_attn_set or block_cfg.self_attn_kwargs is not None:
        if self_attn_set and not block_cfg.self_attn_layer:
            self_attn_layer = None
        else:
            self_attn_kwargs = override_kwargs(block_cfg.self_attn_kwargs, model_cfg.self_attn_kwargs)
            self_attn_layer = block_cfg.self_attn_layer or model_cfg.self_attn_layer
            self_attn_layer = partial(get_attn(self_attn_layer), **self_attn_kwargs) \
                if self_attn_layer is not None else None
        layer_fns = replace(layer_fns, self_attn=self_attn_layer)

    block_kwargs['layers'] = layer_fns
    block_kwargs.update(override_kwargs(block_cfg.block_kwargs, model_cfg.block_kwargs))


def drop_blocks(drop_prob=0., block_size=3, num_stages=4, rngs=None):
    """DropBlock partials for the last two stages (reference byobnet.py:1343)."""
    dbs = [None] * num_stages
    if drop_prob:
        assert num_stages >= 2
        dbs[-2] = partial(DropBlock2d, drop_prob=drop_prob, block_size=block_size * 2 - 1,
                          gamma_scale=0.25, rngs=rngs)
        dbs[-1] = partial(DropBlock2d, drop_prob=drop_prob, block_size=block_size,
                          gamma_scale=1.00, rngs=rngs)
    return dbs


def create_byob_stages(
        cfg: ByoModelCfg,
        drop_path_rate: float,
        output_stride: int,
        stem_feat: Dict[str, Any],
        drop_block_rate: float = 0.,
        drop_block_size: int = 3,
        feat_size=None,
        layers: Optional[LayerFn] = None,
        block_kwargs_fn=update_block_kwargs,
        *, dtype=None, param_dtype=jnp.float32, rngs):
    layers = layers or LayerFn()
    feature_info = []
    block_cfgs = [expand_blocks_cfg(s) for s in cfg.blocks]
    num_stages = len(block_cfgs)
    depths = [sum(bc.d for bc in stage_bcs) for stage_bcs in block_cfgs]
    dpr = calculate_drop_path_rates(drop_path_rate, depths, stagewise=True)
    dbs = drop_blocks(drop_block_rate, drop_block_size, num_stages, rngs=rngs)
    dilation = 1
    net_stride = stem_feat['reduction']
    prev_chs = stem_feat['num_chs']
    prev_feat = stem_feat
    stages = []
    for stage_idx, stage_block_cfgs in enumerate(block_cfgs):
        stride = stage_block_cfgs[0].s
        if stride != 1 and prev_feat:
            feature_info.append(prev_feat)
        if net_stride >= output_stride and stride > 1:
            dilation *= stride
            stride = 1
        net_stride *= stride
        first_dilation = 1 if dilation in (1, 2) else 2

        blocks = []
        for block_idx, block_cfg in enumerate(stage_block_cfgs):
            out_chs = make_divisible(block_cfg.c * cfg.width_factor)
            group_size = block_cfg.gs
            if callable(group_size):
                group_size = group_size(out_chs, block_idx)
            block_kwargs = dict(
                in_chs=prev_chs,
                out_chs=out_chs,
                stride=stride if block_idx == 0 else 1,
                dilation=(first_dilation, dilation),
                group_size=group_size,
                bottle_ratio=block_cfg.br,
                downsample=cfg.downsample,
                drop_block=dbs[stage_idx],
                drop_path_rate=dpr[stage_idx][block_idx],
                layers=layers,
                dtype=dtype,
                param_dtype=param_dtype,
                rngs=rngs,
            )
            if block_cfg.type in ('self_attn',):
                block_kwargs['feat_size'] = feat_size
            block_kwargs_fn(block_kwargs, block_cfg=block_cfg, model_cfg=cfg)
            blocks += [create_block(block_cfg.type, **block_kwargs)]
            first_dilation = dilation
            prev_chs = out_chs
            if stride > 1 and block_idx == 0:
                feat_size = reduce_feat_size(feat_size, stride)

        stages += [nnx.List(blocks)]
        prev_feat = dict(num_chs=prev_chs, reduction=net_stride,
                         module=f'stages.{stage_idx}', stage=stage_idx + 1)

    feature_info.append(prev_feat)
    return nnx.List(stages), feature_info, feat_size


def get_layer_fns(cfg: ByoModelCfg, allow_aa: bool = True) -> LayerFn:
    norm_act = get_norm_act_layer(cfg.norm_layer, act_layer=cfg.act_layer)
    aa = get_aa_layer(cfg.aa_layer) if allow_aa else None
    conv_norm_act = partial(
        ConvNormAct, norm_layer=norm_act, act_layer=cfg.act_layer, padding=None,
        aa_layer=aa)
    attn = partial(get_attn(cfg.attn_layer), **cfg.attn_kwargs) if cfg.attn_layer else None
    self_attn = partial(get_attn(cfg.self_attn_layer), **cfg.self_attn_kwargs) if cfg.self_attn_layer else None
    return LayerFn(conv_norm_act=conv_norm_act, norm_act=norm_act, act=cfg.act_layer,
                   attn=attn, self_attn=self_attn)


class ByobNet(nnx.Module):
    """Bring-your-own-blocks network (reference byobnet.py:1457)."""

    def __init__(
            self,
            cfg: ByoModelCfg,
            num_classes: int = 1000,
            in_chans: int = 3,
            global_pool: Optional[str] = None,
            output_stride: int = 32,
            img_size: Optional[Union[int, Tuple[int, int]]] = None,
            drop_rate: float = 0.,
            drop_block_rate: float = 0.,
            drop_block_size: int = 3,
            drop_path_rate: float = 0.,
            zero_init_last: bool = True,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: Optional[nnx.Rngs] = None,
            **kwargs,
    ):
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.num_classes = num_classes
        self.drop_rate = drop_rate
        self.grad_checkpointing = False
        dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        cfg = replace(cfg, **kwargs)  # overlay kwargs onto cfg
        stem_layers = get_layer_fns(cfg, allow_aa=False)
        stage_layers = get_layer_fns(cfg)
        if cfg.fixed_input_size:
            assert img_size is not None, 'img_size argument is required for fixed input size model'
        feat_size = to_2tuple(img_size) if img_size is not None else None

        self.feature_info = []
        if isinstance(cfg.stem_chs, (list, tuple)):
            stem_chs = [int(round(c * cfg.width_factor)) for c in cfg.stem_chs]
        else:
            stem_chs = int(round((cfg.stem_chs or cfg.blocks[0].c) * cfg.width_factor))
        self.stem, stem_feat = create_byob_stem(
            in_chs=in_chans, out_chs=stem_chs, stem_type=cfg.stem_type,
            pool_type=cfg.stem_pool, layers=stem_layers, **dd)
        self.feature_info.extend(stem_feat[:-1])
        feat_size = reduce_feat_size(feat_size, stride=stem_feat[-1]['reduction'])

        self.stages, stage_feat, feat_size = create_byob_stages(
            cfg, drop_path_rate, output_stride, stem_feat[-1],
            drop_block_rate=drop_block_rate, drop_block_size=drop_block_size,
            layers=stage_layers, feat_size=feat_size, **dd)
        self.feature_info.extend(stage_feat[:-1])
        reduction = stage_feat[-1]['reduction']

        prev_chs = stage_feat[-1]['num_chs']
        if cfg.num_features:
            self.num_features = int(round(cfg.width_factor * cfg.num_features))
            self.final_conv = stage_layers.conv_norm_act(prev_chs, self.num_features, 1, **dd)
        else:
            self.num_features = prev_chs
            self.final_conv = None
        self.feature_info += [dict(
            num_chs=self.num_features, reduction=reduction, module='final_conv',
            stage=len(self.stages))]
        self.stage_ends = [f['stage'] for f in self.feature_info]

        self.head_hidden_size = self.num_features
        assert cfg.head_type in ('', 'classifier', 'mlp', 'attn_abs', 'attn_rot')
        if cfg.head_type == 'mlp':
            global_pool = global_pool if global_pool is not None else 'avg'
            self.head = NormMlpClassifierHead(
                self.num_features, num_classes, hidden_size=cfg.head_hidden_size,
                pool_type=global_pool, drop_rate=drop_rate,
                # bare norm, no activation — matches reference get_norm_layer use
                norm_layer=partial(get_norm_act_layer(cfg.norm_layer), apply_act=False),
                act_layer=cfg.act_layer, **dd)
            self.head_hidden_size = self.head.hidden_size or self.num_features
        elif cfg.head_type == 'attn_abs':
            global_pool = global_pool if global_pool is not None else 'token'
            assert global_pool in ('', 'token')
            self.head = AttentionPool2d(
                self.num_features, embed_dim=cfg.head_hidden_size, out_features=num_classes,
                feat_size=feat_size or 7, pool_type=global_pool, drop_rate=drop_rate,
                qkv_separate=True, **dd)
            self.head_hidden_size = self.head.embed_dim
        elif cfg.head_type == 'attn_rot':
            global_pool = global_pool if global_pool is not None else 'token'
            assert global_pool in ('', 'token')
            self.head = RotAttentionPool2d(
                self.num_features, embed_dim=cfg.head_hidden_size, out_features=num_classes,
                ref_feat_size=feat_size or 7, pool_type=global_pool, drop_rate=drop_rate,
                qkv_separate=True, **dd)
            self.head_hidden_size = self.head.embed_dim
        else:
            global_pool = global_pool if global_pool is not None else 'avg'
            assert cfg.head_hidden_size is None
            self.head = ClassifierHead(
                self.num_features, num_classes, pool_type=global_pool, drop_rate=drop_rate, **dd)
        self.global_pool = global_pool

        if cfg.zero_init_last and zero_init_last:
            for stage in self.stages:
                for b in stage:
                    if hasattr(b, 'zero_init_last'):
                        b.zero_init_last()

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return set()

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^stem',
            blocks=[
                (r'^stages\.(\d+)' if coarse else r'^stages\.(\d+)\.(\d+)', None),
                (r'^final_conv', (99999,)),
            ],
        )

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def get_classifier(self):
        return getattr(self.head, 'fc', None) or getattr(self.head, 'proj', None)

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if isinstance(self.head, (AttentionPool2d, RotAttentionPool2d)):
            self.head.reset(num_classes, pool_type=global_pool, rngs=rngs)
        else:
            self.head.reset(num_classes, global_pool, rngs=rngs)

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        x = self.stem(x)
        for stage in self.stages:
            if self.grad_checkpointing:
                x = checkpoint_seq(stage, x)
            else:
                for b in stage:
                    x = b(x)
        if self.final_conv is not None:
            x = self.final_conv(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        return self.head(x, pre_logits=pre_logits)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
            exclude_final_conv: bool = False):
        assert output_fmt == 'NHWC'
        intermediates = []
        take_indices, max_index = feature_take_indices(len(self.stage_ends), indices)
        take_indices = [self.stage_ends[i] for i in take_indices]
        max_index = self.stage_ends[max_index]

        feat_idx = 0
        if hasattr(self.stem, 'forward_intermediates'):
            x, x_inter = self.stem.forward_intermediates(x)
        else:
            x, x_inter = self.stem(x), None
        if feat_idx in take_indices:
            intermediates.append(x if x_inter is None else x_inter)
        last_idx = self.stage_ends[-1]
        stages = self.stages if not stop_early else self.stages[:max_index]
        for stage in stages:
            feat_idx += 1
            for b in stage:
                x = b(x)
            if not exclude_final_conv and self.final_conv is not None and feat_idx == last_idx:
                x = self.final_conv(x)
            if feat_idx in take_indices:
                intermediates.append(x)

        if intermediates_only:
            return intermediates
        if exclude_final_conv and self.final_conv is not None and feat_idx == last_idx:
            x = self.final_conv(x)
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.stage_ends), indices)
        max_index = self.stage_ends[max_index]
        self.stages = nnx.List(list(self.stages)[:max_index])
        if max_index < self.stage_ends[-1]:
            self.final_conv = None
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


model_cfgs = dict(
    gernet_l=ByoModelCfg(
        blocks=(
            ByoBlockCfg(type='basic', d=1, c=128, s=2, gs=0, br=1.),
            ByoBlockCfg(type='basic', d=2, c=192, s=2, gs=0, br=1.),
            ByoBlockCfg(type='bottle', d=6, c=640, s=2, gs=0, br=1 / 4),
            ByoBlockCfg(type='bottle', d=5, c=640, s=2, gs=1, br=3.),
            ByoBlockCfg(type='bottle', d=4, c=640, s=1, gs=1, br=3.),
        ),
        stem_chs=32,
        stem_pool=None,
        num_features=2560,
    ),
    gernet_m=ByoModelCfg(
        blocks=(
            ByoBlockCfg(type='basic', d=1, c=128, s=2, gs=0, br=1.),
            ByoBlockCfg(type='basic', d=2, c=192, s=2, gs=0, br=1.),
            ByoBlockCfg(type='bottle', d=6, c=640, s=2, gs=0, br=1 / 4),
            ByoBlockCfg(type='bottle', d=4, c=640, s=2, gs=1, br=3.),
            ByoBlockCfg(type='bottle', d=1, c=640, s=1, gs=1, br=3.),
        ),
        stem_chs=32,
        stem_pool=None,
        num_features=2560,
    ),
    gernet_s=ByoModelCfg(
        blocks=(
            ByoBlockCfg(type='basic', d=1, c=48, s=2, gs=0, br=1.),
            ByoBlockCfg(type='basic', d=3, c=48, s=2, gs=0, br=1.),
            ByoBlockCfg(type='bottle', d=7, c=384, s=2, gs=0, br=1 / 4),
            ByoBlockCfg(type='bottle', d=2, c=560, s=2, gs=1, br=3.),
            ByoBlockCfg(type='bottle', d=1, c=256, s=1, gs=1, br=3.),
        ),
        stem_chs=13,
        stem_pool=None,
        num_features=1920,
    ),

    repvgg_a0=ByoModelCfg(
        blocks=_rep_vgg_bcfg(d=(2, 4, 14, 1), wf=(0.75, 0.75, 0.75, 2.5)),
        stem_type='rep',
        stem_chs=48,
    ),
    repvgg_a1=ByoModelCfg(
        blocks=_rep_vgg_bcfg(d=(2, 4, 14, 1), wf=(1, 1, 1, 2.5)),
        stem_type='rep',
        stem_chs=64,
    ),
    repvgg_a2=ByoModelCfg(
        blocks=_rep_vgg_bcfg(d=(2, 4, 14, 1), wf=(1.5, 1.5, 1.5, 2.75)),
        stem_type='rep',
        stem_chs=64,
    ),
    repvgg_b0=ByoModelCfg(
        blocks=_rep_vgg_bcfg(wf=(1., 1., 1., 2.5)),
        stem_type='rep',
        stem_chs=64,
    ),
    repvgg_b1=ByoModelCfg(
        blocks=_rep_vgg_bcfg(wf=(2., 2., 2., 4.)),
        stem_type='rep',
        stem_chs=64,
    ),
    repvgg_b1g4=ByoModelCfg(
        blocks=_rep_vgg_bcfg(wf=(2., 2., 2., 4.), groups=4),
        stem_type='rep',
        stem_chs=64,
    ),
    repvgg_b2=ByoModelCfg(
        blocks=_rep_vgg_bcfg(wf=(2.5, 2.5, 2.5, 5.)),
        stem_type='rep',
        stem_chs=64,
    ),
    repvgg_b2g4=ByoModelCfg(
        blocks=_rep_vgg_bcfg(wf=(2.5, 2.5, 2.5, 5.), groups=4),
        stem_type='rep',
        stem_chs=64,
    ),
    repvgg_b3=ByoModelCfg(
        blocks=_rep_vgg_bcfg(wf=(3., 3., 3., 5.)),
        stem_type='rep',
        stem_chs=64,
    ),
    repvgg_b3g4=ByoModelCfg(
        blocks=_rep_vgg_bcfg(wf=(3., 3., 3., 5.), groups=4),
        stem_type='rep',
        stem_chs=64,
    ),
    repvgg_d2se=ByoModelCfg(
        blocks=_rep_vgg_bcfg(d=(8, 14, 24, 1), wf=(2.5, 2.5, 2.5, 5.)),
        stem_type='rep',
        stem_chs=64,
        attn_layer='se',
        attn_kwargs=dict(rd_ratio=0.0625, rd_divisor=1),
    ),

    resnet51q=ByoModelCfg(
        blocks=(
            ByoBlockCfg(type='bottle', d=2, c=256, s=1, gs=32, br=0.25),
            ByoBlockCfg(type='bottle', d=4, c=512, s=2, gs=32, br=0.25),
            ByoBlockCfg(type='bottle', d=6, c=1536, s=2, gs=32, br=0.25),
            ByoBlockCfg(type='bottle', d=4, c=1536, s=2, gs=1, br=1.0),
        ),
        stem_chs=128,
        stem_type='quad2',
        stem_pool=None,
        num_features=2048,
        act_layer='silu',
    ),
    resnet61q=ByoModelCfg(
        blocks=(
            ByoBlockCfg(type='edge', d=1, c=256, s=1, gs=0, br=1.0, block_kwargs=dict()),
            ByoBlockCfg(type='bottle', d=4, c=512, s=2, gs=32, br=0.25),
            ByoBlockCfg(type='bottle', d=6, c=1536, s=2, gs=32, br=0.25),
            ByoBlockCfg(type='bottle', d=4, c=1536, s=2, gs=1, br=1.0),
        ),
        stem_chs=128,
        stem_type='quad',
        stem_pool=None,
        num_features=2048,
        act_layer='silu',
        block_kwargs=dict(extra_conv=True),
    ),

    resnext26ts=ByoModelCfg(
        blocks=(
            ByoBlockCfg(type='bottle', d=2, c=256, s=1, gs=32, br=0.25),
            ByoBlockCfg(type='bottle', d=2, c=512, s=2, gs=32, br=0.25),
            ByoBlockCfg(type='bottle', d=2, c=1024, s=2, gs=32, br=0.25),
            ByoBlockCfg(type='bottle', d=2, c=2048, s=2, gs=32, br=0.25),
        ),
        stem_chs=64,
        stem_type='tiered',
        stem_pool='maxpool',
        act_layer='silu',
    ),
)

# the resnext26ts skeleton with different attn layers
model_cfgs['gcresnext26ts'] = replace(model_cfgs['resnext26ts'], attn_layer='gca')
model_cfgs['seresnext26ts'] = replace(model_cfgs['resnext26ts'], attn_layer='se')
model_cfgs['eca_resnext26ts'] = replace(model_cfgs['resnext26ts'], attn_layer='eca')
model_cfgs['bat_resnext26ts'] = replace(
    model_cfgs['resnext26ts'], attn_layer='bat', attn_kwargs=dict(block_size=8))

_resnet33ts_blocks = (
    ByoBlockCfg(type='bottle', d=2, c=256, s=1, gs=0, br=0.25),
    ByoBlockCfg(type='bottle', d=3, c=512, s=2, gs=0, br=0.25),
    ByoBlockCfg(type='bottle', d=3, c=1536, s=2, gs=0, br=0.25),
    ByoBlockCfg(type='bottle', d=2, c=1536, s=2, gs=0, br=0.25),
)
model_cfgs.update(
    resnet32ts=ByoModelCfg(
        blocks=_resnet33ts_blocks,
        stem_chs=64, stem_type='tiered', stem_pool='', num_features=0, act_layer='silu'),
    resnet33ts=ByoModelCfg(
        blocks=_resnet33ts_blocks,
        stem_chs=64, stem_type='tiered', stem_pool='', num_features=1280, act_layer='silu'),
)
model_cfgs['gcresnet33ts'] = replace(model_cfgs['resnet33ts'], attn_layer='gca')
model_cfgs['seresnet33ts'] = replace(model_cfgs['resnet33ts'], attn_layer='se')
model_cfgs['eca_resnet33ts'] = replace(model_cfgs['resnet33ts'], attn_layer='eca')

model_cfgs.update(
    gcresnet50t=ByoModelCfg(
        blocks=(
            ByoBlockCfg(type='bottle', d=3, c=256, s=1, br=0.25),
            ByoBlockCfg(type='bottle', d=4, c=512, s=2, br=0.25),
            ByoBlockCfg(type='bottle', d=6, c=1024, s=2, br=0.25),
            ByoBlockCfg(type='bottle', d=3, c=2048, s=2, br=0.25),
        ),
        stem_chs=64, stem_type='tiered', stem_pool='', attn_layer='gca'),
    gcresnext50ts=ByoModelCfg(
        blocks=(
            ByoBlockCfg(type='bottle', d=3, c=256, s=1, gs=32, br=0.25),
            ByoBlockCfg(type='bottle', d=4, c=512, s=2, gs=32, br=0.25),
            ByoBlockCfg(type='bottle', d=6, c=1024, s=2, gs=32, br=0.25),
            ByoBlockCfg(type='bottle', d=3, c=2048, s=2, gs=32, br=0.25),
        ),
        stem_chs=64, stem_type='tiered', stem_pool='maxpool', act_layer='silu', attn_layer='gca'),
)


def _regnetz_cfg(depths, chs, gs, br, stem_chs, stem_type='', num_features=1536,
                 first_stride=2, norm_layer='batchnorm'):
    return ByoModelCfg(
        blocks=tuple(
            ByoBlockCfg(type='bottle', d=d, c=c, s=(first_stride if i == 0 else 2), gs=gs, br=br)
            for i, (d, c) in enumerate(zip(depths, chs))),
        stem_chs=stem_chs,
        stem_type=stem_type,
        stem_pool='',
        downsample='',
        num_features=num_features,
        act_layer='silu',
        norm_layer=norm_layer,
        attn_layer='se',
        attn_kwargs=dict(rd_ratio=0.25),
        block_kwargs=dict(bottle_in=True, linear_out=True),
    )


model_cfgs.update(
    regnetz_b16=_regnetz_cfg((2, 6, 12, 2), (48, 96, 192, 288), 16, 3, 32),
    regnetz_c16=_regnetz_cfg((2, 6, 12, 2), (48, 96, 192, 288), 16, 4, 32),
    regnetz_d32=_regnetz_cfg((3, 6, 12, 3), (64, 128, 256, 384), 32, 4, 64,
                             stem_type='tiered', num_features=1792, first_stride=1),
    regnetz_d8=_regnetz_cfg((3, 6, 12, 3), (64, 128, 256, 384), 8, 4, 64,
                            stem_type='tiered', num_features=1792, first_stride=1),
    regnetz_e8=_regnetz_cfg((3, 8, 16, 3), (96, 192, 384, 512), 8, 4, 64,
                            stem_type='tiered', num_features=2048, first_stride=1),
)
# EvoNorm-S0a variants (norm carries its own act; group_size 16)
from ..layers import EvoNorm2dS0a  # noqa: E402
_evos = partial(EvoNorm2dS0a, group_size=16)
model_cfgs.update(
    regnetz_b16_evos=replace(model_cfgs['regnetz_b16'], norm_layer=_evos),
    regnetz_c16_evos=replace(model_cfgs['regnetz_c16'], norm_layer=_evos),
    regnetz_d8_evos=replace(model_cfgs['regnetz_d8'], norm_layer=_evos, stem_type='deep'),
)

model_cfgs.update(
    mobileone_s0=ByoModelCfg(
        blocks=_mobileone_bcfg(wf=(0.75, 1.0, 1.0, 2.), num_conv_branches=4),
        stem_type='one', stem_chs=48),
    mobileone_s1=ByoModelCfg(
        blocks=_mobileone_bcfg(wf=(1.5, 1.5, 2.0, 2.5)), stem_type='one', stem_chs=64),
    mobileone_s2=ByoModelCfg(
        blocks=_mobileone_bcfg(wf=(1.5, 2.0, 2.5, 4.0)), stem_type='one', stem_chs=64),
    mobileone_s3=ByoModelCfg(
        blocks=_mobileone_bcfg(wf=(2.0, 2.5, 3.0, 4.0)), stem_type='one', stem_chs=64),
    mobileone_s4=ByoModelCfg(
        blocks=_mobileone_bcfg(wf=(3.0, 3.5, 3.5, 4.0), se_blocks=(0, 0, 5, 1)),
        stem_type='one', stem_chs=64),
)


def _clip_cfg(depths, width_factor=1.0, head_type='attn_abs', head_hidden_size=None):
    return ByoModelCfg(
        blocks=tuple(
            ByoBlockCfg(type='bottle', d=d, c=c, s=(1 if i == 0 else 2), br=0.25)
            for i, (d, c) in enumerate(zip(depths, (256, 512, 1024, 2048)))),
        width_factor=width_factor,
        stem_chs=(32, 32, 64),
        stem_type='',
        stem_pool='avg2',
        downsample='avg',
        aa_layer='avg',
        head_type=head_type,
        head_hidden_size=head_hidden_size,
        fixed_input_size=(head_type == 'attn_abs'),
    )


model_cfgs.update(
    resnet50_clip=_clip_cfg((3, 4, 6, 3)),
    resnet101_clip=_clip_cfg((3, 4, 23, 3)),
    resnet50x4_clip=_clip_cfg((4, 6, 10, 6), width_factor=1.25),
    resnet50x16_clip=_clip_cfg((6, 8, 18, 8), width_factor=1.5),
    resnet50x64_clip=_clip_cfg((3, 15, 36, 10), width_factor=2.0),
    resnet50_mlp=_clip_cfg((3, 4, 6, 3), head_type='mlp', head_hidden_size=1024),
    test_byobnet=ByoModelCfg(
        blocks=(
            ByoBlockCfg(type='edge', d=1, c=32, s=2, gs=0, br=0.5),
            ByoBlockCfg(type='dark', d=1, c=64, s=2, gs=0, br=0.5),
            ByoBlockCfg(type='basic', d=1, c=128, s=2, gs=32, br=0.25),
            ByoBlockCfg(type='bottle', d=1, c=256, s=2, gs=64, br=0.25),
        ),
        stem_chs=24,
        downsample='avg',
        stem_pool='',
        act_layer='relu',
        attn_layer='se',
        attn_kwargs=dict(rd_ratio=0.25),
    ),
)
for _k in ('resnet50_clip', 'resnet101_clip', 'resnet50x4_clip', 'resnet50x16_clip', 'resnet50x64_clip'):
    model_cfgs[_k + '_gap'] = replace(model_cfgs[_k], head_type='classifier', fixed_input_size=False)


def checkpoint_filter_fn(state_dict, model):
    """Reference-timm byobnet state dicts map almost 1:1 onto this module tree;
    only the NormMlp head naming differs (reference `head.pre_logits.fc`)."""
    import re
    from ._torch_convert import convert_torch_state_dict
    out = {}
    for k, v in state_dict.items():
        k = re.sub(r'^head\.pre_logits\.fc\.', 'head.pre_logits_fc.', k)
        out[k] = v
    return convert_torch_state_dict(out, model)


def _create_byobnet(variant: str, pretrained: bool = False, **kwargs) -> ByobNet:
    return build_model_with_cfg(
        ByobNet, variant, pretrained,
        model_cfg=model_cfgs[variant],
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(flatten_sequential=True),
        **kwargs,
    )


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000,
        'input_size': (3, 224, 224),
        'pool_size': (7, 7),
        'crop_pct': 0.875,
        'interpolation': 'bilinear',
        'mean': (0.485, 0.456, 0.406),
        'std': (0.229, 0.224, 0.225),
        'first_conv': 'stem.conv',
        'classifier': 'head.fc',
        **kwargs,
    }


def _cfgr(url: str = '', **kwargs) -> Dict[str, Any]:
    return _cfg(url, **{
        'input_size': (3, 256, 256), 'pool_size': (8, 8),
        'interpolation': 'bicubic', 'first_conv': 'stem.conv1.conv', **kwargs})


_CLIP_KW = dict(
    num_classes=1024, mean=(0.48145466, 0.4578275, 0.40821073),
    std=(0.26862954, 0.26130258, 0.27577711), interpolation='bicubic',
    first_conv='stem.conv1.conv', classifier='head.proj', fixed_input_size=True)

default_cfgs = generate_default_cfgs({
    'gernet_s.idstcv_in1k': _cfg(first_conv='stem.conv'),
    'gernet_m.idstcv_in1k': _cfg(first_conv='stem.conv'),
    'gernet_l.idstcv_in1k': _cfg(input_size=(3, 256, 256), pool_size=(8, 8), first_conv='stem.conv'),
    'repvgg_a0.rvgg_in1k': _cfg(first_conv='stem.conv_kxk.conv'),
    'repvgg_a1.rvgg_in1k': _cfg(first_conv='stem.conv_kxk.conv'),
    'repvgg_a2.rvgg_in1k': _cfg(first_conv='stem.conv_kxk.conv'),
    'repvgg_b0.rvgg_in1k': _cfg(first_conv='stem.conv_kxk.conv'),
    'repvgg_b1.rvgg_in1k': _cfg(first_conv='stem.conv_kxk.conv'),
    'repvgg_b1g4.rvgg_in1k': _cfg(first_conv='stem.conv_kxk.conv'),
    'repvgg_b2.rvgg_in1k': _cfg(first_conv='stem.conv_kxk.conv'),
    'repvgg_b2g4.rvgg_in1k': _cfg(first_conv='stem.conv_kxk.conv'),
    'repvgg_b3.rvgg_in1k': _cfg(first_conv='stem.conv_kxk.conv'),
    'repvgg_b3g4.rvgg_in1k': _cfg(first_conv='stem.conv_kxk.conv'),
    'repvgg_d2se.rvgg_in1k': _cfg(
        first_conv='stem.conv_kxk.conv', input_size=(3, 320, 320), pool_size=(10, 10)),
    'resnet51q.ra2_in1k': _cfg(
        first_conv='stem.conv1.conv', input_size=(3, 256, 256), pool_size=(8, 8),
        interpolation='bicubic'),
    'resnet61q.ra2_in1k': _cfgr(),
    'resnext26ts.ra2_in1k': _cfgr(),
    'seresnext26ts.ch_in1k': _cfgr(),
    'gcresnext26ts.ch_in1k': _cfgr(),
    'eca_resnext26ts.ch_in1k': _cfgr(),
    'bat_resnext26ts.ch_in1k': _cfgr(min_input_size=(3, 256, 256)),
    'resnet32ts.ra2_in1k': _cfgr(),
    'resnet33ts.ra2_in1k': _cfgr(),
    'gcresnet33ts.ra2_in1k': _cfgr(),
    'seresnet33ts.ra2_in1k': _cfgr(),
    'eca_resnet33ts.ra2_in1k': _cfgr(),
    'gcresnet50t.ra2_in1k': _cfgr(),
    'gcresnext50ts.ch_in1k': _cfgr(),
    'regnetz_b16.ra3_in1k': _cfgr(input_size=(3, 224, 224), pool_size=(7, 7)),
    'regnetz_c16.ra3_in1k': _cfgr(),
    'regnetz_d32.ra3_in1k': _cfgr(input_size=(3, 320, 320), pool_size=(10, 10)),
    'regnetz_d8.ra3_in1k': _cfgr(input_size=(3, 320, 320), pool_size=(10, 10)),
    'regnetz_e8.ra3_in1k': _cfgr(input_size=(3, 320, 320), pool_size=(10, 10)),
    'regnetz_b16_evos.untrained': _cfgr(input_size=(3, 224, 224), pool_size=(7, 7)),
    'regnetz_c16_evos.ch_in1k': _cfgr(),
    'regnetz_d8_evos.ch_in1k': _cfgr(input_size=(3, 320, 320), pool_size=(10, 10)),
    'mobileone_s0.apple_in1k': _cfg(first_conv='stem.conv_kxk.0.conv'),
    'mobileone_s1.apple_in1k': _cfg(first_conv='stem.conv_kxk.0.conv'),
    'mobileone_s2.apple_in1k': _cfg(first_conv='stem.conv_kxk.0.conv'),
    'mobileone_s3.apple_in1k': _cfg(first_conv='stem.conv_kxk.0.conv'),
    'mobileone_s4.apple_in1k': _cfg(first_conv='stem.conv_kxk.0.conv'),
    'resnet50_clip.openai': _cfg(**_CLIP_KW),
    'resnet101_clip.openai': _cfg(**{**_CLIP_KW, 'num_classes': 512}),
    'resnet50x4_clip.openai': _cfg(**{**_CLIP_KW, 'num_classes': 640, 'input_size': (3, 288, 288), 'pool_size': (9, 9)}),
    'resnet50x16_clip.openai': _cfg(**{**_CLIP_KW, 'num_classes': 768, 'input_size': (3, 384, 384), 'pool_size': (12, 12)}),
    'resnet50x64_clip.openai': _cfg(**{**_CLIP_KW, 'num_classes': 1024, 'input_size': (3, 448, 448), 'pool_size': (14, 14)}),
    'resnet50_clip_gap.openai': _cfg(num_classes=0, first_conv='stem.conv1.conv'),
    'resnet101_clip_gap.openai': _cfg(num_classes=0, first_conv='stem.conv1.conv'),
    'resnet50x4_clip_gap.openai': _cfg(num_classes=0, first_conv='stem.conv1.conv', input_size=(3, 288, 288)),
    'resnet50x16_clip_gap.openai': _cfg(num_classes=0, first_conv='stem.conv1.conv', input_size=(3, 384, 384)),
    'resnet50x64_clip_gap.openai': _cfg(num_classes=0, first_conv='stem.conv1.conv', input_size=(3, 448, 448)),
    'resnet50_mlp.untrained': _cfg(num_classes=0, first_conv='stem.conv1.conv'),
    'test_byobnet.r160_in1k': _cfg(
        first_conv='stem.conv', input_size=(3, 160, 160), crop_pct=0.95, pool_size=(5, 5)),
})


@register_model
def gernet_l(pretrained=False, **kwargs) -> ByobNet:
    """GEResNet-Large (GENet https://arxiv.org/abs/2006.14090)."""
    return _create_byobnet('gernet_l', pretrained=pretrained, **kwargs)


@register_model
def gernet_m(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('gernet_m', pretrained=pretrained, **kwargs)


@register_model
def gernet_s(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('gernet_s', pretrained=pretrained, **kwargs)


@register_model
def repvgg_a0(pretrained=False, **kwargs) -> ByobNet:
    """RepVGG-A0 (https://arxiv.org/abs/2101.03697)."""
    return _create_byobnet('repvgg_a0', pretrained=pretrained, **kwargs)


@register_model
def repvgg_a1(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('repvgg_a1', pretrained=pretrained, **kwargs)


@register_model
def repvgg_a2(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('repvgg_a2', pretrained=pretrained, **kwargs)


@register_model
def repvgg_b0(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('repvgg_b0', pretrained=pretrained, **kwargs)


@register_model
def repvgg_b1(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('repvgg_b1', pretrained=pretrained, **kwargs)


@register_model
def repvgg_b1g4(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('repvgg_b1g4', pretrained=pretrained, **kwargs)


@register_model
def repvgg_b2(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('repvgg_b2', pretrained=pretrained, **kwargs)


@register_model
def repvgg_b2g4(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('repvgg_b2g4', pretrained=pretrained, **kwargs)


@register_model
def repvgg_b3(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('repvgg_b3', pretrained=pretrained, **kwargs)


@register_model
def repvgg_b3g4(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('repvgg_b3g4', pretrained=pretrained, **kwargs)


@register_model
def repvgg_d2se(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('repvgg_d2se', pretrained=pretrained, **kwargs)


@register_model
def resnet51q(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('resnet51q', pretrained=pretrained, **kwargs)


@register_model
def resnet61q(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('resnet61q', pretrained=pretrained, **kwargs)


@register_model
def resnext26ts(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('resnext26ts', pretrained=pretrained, **kwargs)


@register_model
def gcresnext26ts(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('gcresnext26ts', pretrained=pretrained, **kwargs)


@register_model
def seresnext26ts(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('seresnext26ts', pretrained=pretrained, **kwargs)


@register_model
def eca_resnext26ts(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('eca_resnext26ts', pretrained=pretrained, **kwargs)


@register_model
def resnet32ts(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('resnet32ts', pretrained=pretrained, **kwargs)


@register_model
def resnet33ts(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('resnet33ts', pretrained=pretrained, **kwargs)


@register_model
def gcresnet33ts(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('gcresnet33ts', pretrained=pretrained, **kwargs)


@register_model
def seresnet33ts(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('seresnet33ts', pretrained=pretrained, **kwargs)


@register_model
def eca_resnet33ts(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('eca_resnet33ts', pretrained=pretrained, **kwargs)


@register_model
def gcresnet50t(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('gcresnet50t', pretrained=pretrained, **kwargs)


@register_model
def gcresnext50ts(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('gcresnext50ts', pretrained=pretrained, **kwargs)


@register_model
def regnetz_b16(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('regnetz_b16', pretrained=pretrained, **kwargs)


@register_model
def regnetz_c16(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('regnetz_c16', pretrained=pretrained, **kwargs)


@register_model
def regnetz_d32(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('regnetz_d32', pretrained=pretrained, **kwargs)


@register_model
def regnetz_d8(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('regnetz_d8', pretrained=pretrained, **kwargs)


@register_model
def regnetz_e8(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('regnetz_e8', pretrained=pretrained, **kwargs)


@register_model
def regnetz_b16_evos(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('regnetz_b16_evos', pretrained=pretrained, **kwargs)


@register_model
def regnetz_c16_evos(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('regnetz_c16_evos', pretrained=pretrained, **kwargs)


@register_model
def regnetz_d8_evos(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('regnetz_d8_evos', pretrained=pretrained, **kwargs)


@register_model
def mobileone_s0(pretrained=False, **kwargs) -> ByobNet:
    """MobileOne-S0 (https://arxiv.org/abs/2206.04040)."""
    return _create_byobnet('mobileone_s0', pretrained=pretrained, **kwargs)


@register_model
def mobileone_s1(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('mobileone_s1', pretrained=pretrained, **kwargs)


@register_model
def mobileone_s2(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('mobileone_s2', pretrained=pretrained, **kwargs)


@register_model
def mobileone_s3(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('mobileone_s3', pretrained=pretrained, **kwargs)


@register_model
def mobileone_s4(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('mobileone_s4', pretrained=pretrained, **kwargs)


@register_model
def resnet50_clip(pretrained=False, **kwargs) -> ByobNet:
    """OpenAI CLIP image tower, attention-pool head."""
    return _create_byobnet('resnet50_clip', pretrained=pretrained, **kwargs)


@register_model
def resnet101_clip(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('resnet101_clip', pretrained=pretrained, **kwargs)


@register_model
def resnet50x4_clip(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('resnet50x4_clip', pretrained=pretrained, **kwargs)


@register_model
def resnet50x16_clip(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('resnet50x16_clip', pretrained=pretrained, **kwargs)


@register_model
def resnet50x64_clip(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('resnet50x64_clip', pretrained=pretrained, **kwargs)


@register_model
def resnet50_clip_gap(pretrained=False, **kwargs) -> ByobNet:
    """CLIP image tower as a plain GAP backbone."""
    return _create_byobnet('resnet50_clip_gap', pretrained=pretrained, **kwargs)


@register_model
def resnet101_clip_gap(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('resnet101_clip_gap', pretrained=pretrained, **kwargs)


@register_model
def resnet50x4_clip_gap(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('resnet50x4_clip_gap', pretrained=pretrained, **kwargs)


@register_model
def resnet50x16_clip_gap(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('resnet50x16_clip_gap', pretrained=pretrained, **kwargs)


@register_model
def resnet50x64_clip_gap(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('resnet50x64_clip_gap', pretrained=pretrained, **kwargs)


@register_model
def resnet50_mlp(pretrained=False, **kwargs) -> ByobNet:
    return _create_byobnet('resnet50_mlp', pretrained=pretrained, **kwargs)


@register_model
def test_byobnet(pretrained=False, **kwargs) -> ByobNet:
    """Minimal test model exercising all four residual block types."""
    return _create_byobnet('test_byobnet', pretrained=pretrained, **kwargs)


@register_model
def bat_resnext26ts(pretrained=False, **kwargs) -> ByobNet:
    """ResNeXt-26-TS with Bilinear-Attention-Transform attention."""
    return _create_byobnet('bat_resnext26ts', pretrained=pretrained, **kwargs)
