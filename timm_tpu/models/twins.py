"""Twins — spatially separable attention ViTs, PCPVT + SVT (NHWC / nnx).

Re-implements reference timm/models/twins.py:1-630 (Twins): a four-stage
pyramid with per-stage patch embeds, conditional position encoding (PEG conv
after the first block of each stage), and blocks alternating locally-grouped
window attention (LSA) with global sub-sampled attention (GSA, keys/values
from an sr-strided conv summary).

TPU notes: tokens carry their (H, W) size as static Python ints so every
window partition / sr-conv reshape is a static reshape-transpose; LSA runs as
one batched matmul over (B x windows) and GSA's kv summary is a strided conv
on the MXU. PEG is a 3x3 depthwise conv on the NHWC token grid.
"""
import math
from functools import partial
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
from flax import nnx

from timm_tpu.data.constants import IMAGENET_DEFAULT_MEAN, IMAGENET_DEFAULT_STD
from ..layers import (
    Dropout, DropPath, LayerNorm, Mlp, calculate_drop_path_rates, to_2tuple,
    trunc_normal_, zeros_,
)
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._registry import generate_default_cfgs, register_model

__all__ = ['Twins']

Size_ = Tuple[int, int]


def _linear(in_f, out_f, bias=True, *, dtype, param_dtype, rngs):
    return nnx.Linear(in_f, out_f, use_bias=bias, kernel_init=trunc_normal_(std=0.02),
                      bias_init=zeros_, dtype=dtype, param_dtype=param_dtype, rngs=rngs)


def _conv(in_c, out_c, k, s, p=0, groups=1, *, dtype, param_dtype, rngs):
    # torch reference init (twins.py:449-451): plain normal, std=sqrt(2/fan_out)
    # with fan_out divided by groups — flax's variance_scaling would compute
    # fan_out from the full kernel and under-scale depthwise (PEG) convs
    fan_out = (k * k * out_c) // groups
    kernel_init = jax.nn.initializers.normal(stddev=math.sqrt(2.0 / fan_out))
    return nnx.Conv(
        in_c, out_c, kernel_size=(k, k), strides=s, padding=[(p, p), (p, p)],
        feature_group_count=groups, use_bias=True,
        kernel_init=kernel_init, bias_init=zeros_,
        dtype=dtype, param_dtype=param_dtype, rngs=rngs)


class LocallyGroupedAttn(nnx.Module):
    """LSA: self-attention within ws x ws windows (reference twins.py:36-106)."""

    def __init__(self, dim, num_heads=8, attn_drop=0., proj_drop=0., ws=1,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        assert ws != 1 and dim % num_heads == 0
        self.dim = dim
        self.num_heads = num_heads
        self.scale = (dim // num_heads) ** -0.5
        self.ws = ws
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.qkv = _linear(dim, dim * 3, **kw)
        self.attn_drop = Dropout(attn_drop, rngs=rngs)
        self.proj = _linear(dim, dim, **kw)
        self.proj_drop = Dropout(proj_drop, rngs=rngs)

    def __call__(self, x, size: Size_):
        B, N, C = x.shape
        H, W = size
        ws = self.ws
        x = x.reshape(B, H, W, C)
        pad_r = (ws - W % ws) % ws
        pad_b = (ws - H % ws) % ws
        if pad_r or pad_b:
            x = jnp.pad(x, ((0, 0), (0, pad_b), (0, pad_r), (0, 0)))
        Hp, Wp = H + pad_b, W + pad_r
        _h, _w = Hp // ws, Wp // ws
        x = x.reshape(B, _h, ws, _w, ws, C).transpose(0, 1, 3, 2, 4, 5)  # (B,_h,_w,ws,ws,C)
        qkv = self.qkv(x).reshape(B, _h * _w, ws * ws, 3, self.num_heads, C // self.num_heads)
        q, k, v = qkv[:, :, :, 0], qkv[:, :, :, 1], qkv[:, :, :, 2]  # (B,G,P,nh,hd)
        attn = jnp.einsum('bgnhd,bgmhd->bghnm', q, k) * self.scale
        attn = self.attn_drop(jax.nn.softmax(attn, axis=-1))
        x = jnp.einsum('bghnm,bgmhd->bgnhd', attn, v).reshape(B, _h, _w, ws, ws, C)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(B, Hp, Wp, C)
        if pad_r or pad_b:
            x = x[:, :H, :W]
        x = self.proj(x.reshape(B, N, C))
        return self.proj_drop(x)


class GlobalSubSampleAttn(nnx.Module):
    """GSA: queries over all tokens, keys/values from an sr-strided conv
    summary (reference twins.py:145-210)."""

    def __init__(self, dim, num_heads=8, attn_drop=0., proj_drop=0., sr_ratio=1,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        assert dim % num_heads == 0
        self.dim = dim
        self.num_heads = num_heads
        self.scale = (dim // num_heads) ** -0.5
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.q = _linear(dim, dim, **kw)
        self.kv = _linear(dim, dim * 2, **kw)
        self.attn_drop = Dropout(attn_drop, rngs=rngs)
        self.proj = _linear(dim, dim, **kw)
        self.proj_drop = Dropout(proj_drop, rngs=rngs)
        self.sr_ratio = sr_ratio
        if sr_ratio > 1:
            self.sr = _conv(dim, dim, sr_ratio, sr_ratio, **kw)
            self.norm = LayerNorm(dim, eps=1e-5, rngs=rngs)  # plain nn.LayerNorm in reference
        else:
            self.sr = None
            self.norm = None

    def __call__(self, x, size: Size_):
        B, N, C = x.shape
        hd = C // self.num_heads
        q = self.q(x).reshape(B, N, self.num_heads, hd)
        if self.sr is not None:
            x = self.sr(x.reshape(B, *size, C)).reshape(B, -1, C)
            x = self.norm(x)
        kv = self.kv(x).reshape(B, -1, 2, self.num_heads, hd)
        k, v = kv[:, :, 0], kv[:, :, 1]
        attn = jnp.einsum('bnhd,bmhd->bhnm', q, k) * self.scale
        attn = self.attn_drop(jax.nn.softmax(attn, axis=-1))
        x = jnp.einsum('bhnm,bmhd->bnhd', attn, v).reshape(B, N, C)
        return self.proj_drop(self.proj(x))


class TwinsBlock(nnx.Module):
    """Pre-norm block with LSA/GSA mixer (reference twins.py:212-262)."""

    def __init__(self, dim, num_heads, mlp_ratio=4., proj_drop=0., attn_drop=0.,
                 drop_path=0., act_layer='gelu', norm_layer=None, sr_ratio=1, ws=None,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        norm_layer = norm_layer or partial(LayerNorm, eps=1e-6)
        self.norm1 = norm_layer(dim, rngs=rngs)
        assert ws is not None, 'Twins entrypoints always set ws (1 = GSA)'
        if ws == 1:
            self.attn = GlobalSubSampleAttn(dim, num_heads, attn_drop, proj_drop, sr_ratio, **kw)
        else:
            self.attn = LocallyGroupedAttn(dim, num_heads, attn_drop, proj_drop, ws, **kw)
        self.drop_path1 = DropPath(drop_path, rngs=rngs) if drop_path > 0. else None
        self.norm2 = norm_layer(dim, rngs=rngs)
        self.mlp = Mlp(dim, int(dim * mlp_ratio), act_layer=act_layer, drop=proj_drop, **kw)
        self.drop_path2 = DropPath(drop_path, rngs=rngs) if drop_path > 0. else None

    def __call__(self, x, size: Size_):
        y = self.attn(self.norm1(x), size)
        x = x + (self.drop_path1(y) if self.drop_path1 is not None else y)
        y = self.mlp(self.norm2(x))
        return x + (self.drop_path2(y) if self.drop_path2 is not None else y)


class PosConv(nnx.Module):
    """PEG conditional position encoding: 3x3 dw conv over the token grid,
    residual at stride 1 (reference twins.py:265-292)."""

    def __init__(self, in_chans, embed_dim=768, stride=1,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        # single conv wrapped in a list to mirror the torch nn.Sequential key (proj.0)
        self.proj = nnx.List([
            _conv(in_chans, embed_dim, 3, stride, 1, groups=embed_dim,
                  dtype=dtype, param_dtype=param_dtype, rngs=rngs)])
        self.stride = stride

    def __call__(self, x, size: Size_):
        B, N, C = x.shape
        feat = x.reshape(B, *size, C)
        out = self.proj[0](feat)
        if self.stride == 1:
            out = out + feat
        return out.reshape(B, N, C)


class TwinsPatchEmbed(nnx.Module):
    """Per-stage conv patch embed + LN (reference twins.py:295-332)."""

    def __init__(self, img_size=224, patch_size=16, in_chans=3, embed_dim=768,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        img_size = to_2tuple(img_size)
        patch_size = to_2tuple(patch_size)
        assert img_size[0] % patch_size[0] == 0 and img_size[1] % patch_size[1] == 0
        self.img_size = img_size
        self.patch_size = patch_size
        self.H, self.W = img_size[0] // patch_size[0], img_size[1] // patch_size[1]
        self.num_patches = self.H * self.W
        fan_out = patch_size[0] * patch_size[1] * embed_dim
        self.proj = nnx.Conv(
            in_chans, embed_dim, kernel_size=patch_size, strides=patch_size, padding='VALID',
            kernel_init=jax.nn.initializers.normal(stddev=math.sqrt(2.0 / fan_out)),
            bias_init=zeros_, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.norm = LayerNorm(embed_dim, eps=1e-5, rngs=rngs)  # plain nn.LayerNorm

    def __call__(self, x):
        B, H, W, C = x.shape
        x = self.proj(x)
        out_size = (H // self.patch_size[0], W // self.patch_size[1])
        x = x.reshape(B, -1, x.shape[-1])
        return self.norm(x), out_size


class Twins(nnx.Module):
    """Twins PCPVT / SVT (reference twins.py:335-549)."""

    def __init__(
            self,
            img_size: Union[int, Tuple[int, int]] = 224,
            patch_size: int = 4,
            in_chans: int = 3,
            num_classes: int = 1000,
            global_pool: str = 'avg',
            embed_dims: Tuple[int, ...] = (64, 128, 256, 512),
            num_heads: Tuple[int, ...] = (1, 2, 4, 8),
            mlp_ratios: Tuple[float, ...] = (4, 4, 4, 4),
            depths: Tuple[int, ...] = (3, 4, 6, 3),
            sr_ratios: Tuple[int, ...] = (8, 4, 2, 1),
            wss: Optional[Tuple[int, ...]] = None,
            drop_rate: float = 0.,
            pos_drop_rate: float = 0.,
            proj_drop_rate: float = 0.,
            attn_drop_rate: float = 0.,
            drop_path_rate: float = 0.,
            norm_layer=partial(LayerNorm, eps=1e-6),
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: Optional[nnx.Rngs] = None,
    ):
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.num_classes = num_classes
        self.global_pool = global_pool
        self.depths = depths
        self.embed_dims = embed_dims
        self.num_features = self.head_hidden_size = embed_dims[-1]
        self._dd = dict(dtype=dtype, param_dtype=param_dtype)

        img_size = to_2tuple(img_size)
        prev_chs = in_chans
        patch_embeds = []
        pos_drops = []
        ps = patch_size
        for i in range(len(depths)):
            patch_embeds.append(TwinsPatchEmbed(img_size, ps, prev_chs, embed_dims[i], **kw))
            pos_drops.append(Dropout(pos_drop_rate, rngs=rngs))
            prev_chs = embed_dims[i]
            img_size = tuple(t // ps for t in img_size)
            ps = 2
        self.patch_embeds = nnx.List(patch_embeds)
        self.pos_drops = nnx.List(pos_drops)

        blocks = []
        self.feature_info = []
        dpr = calculate_drop_path_rates(drop_path_rate, sum(depths))
        cur = 0
        for k in range(len(depths)):
            stage_blocks = nnx.List([
                TwinsBlock(
                    dim=embed_dims[k], num_heads=num_heads[k], mlp_ratio=mlp_ratios[k],
                    proj_drop=proj_drop_rate, attn_drop=attn_drop_rate,
                    drop_path=dpr[cur + i], norm_layer=norm_layer, sr_ratio=sr_ratios[k],
                    ws=1 if wss is None or i % 2 == 1 else wss[k], **kw)
                for i in range(depths[k])])
            blocks.append(stage_blocks)
            self.feature_info += [dict(module=f'block.{k}', num_chs=embed_dims[k], reduction=2 ** (2 + k))]
            cur += depths[k]
        self.blocks = nnx.List(blocks)

        self.pos_block = nnx.List([
            PosConv(embed_dim, embed_dim, **kw) for embed_dim in embed_dims])
        self.norm = norm_layer(self.num_features, rngs=rngs)
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        self.head = _linear(self.num_features, num_classes, **kw) if num_classes > 0 else None

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return {'pos_block'}

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^patch_embeds.0',
            blocks=[
                (r'^(?:blocks|patch_embeds|pos_block)\.(\d+)', None),
                (r'^norm', (99999,)),
            ] if coarse else [
                (r'^blocks\.(\d+)\.(\d+)', None),
                (r'^(?:patch_embeds|pos_block)\.(\d+)', (0,)),
                (r'^norm', (99999,)),
            ])

    def set_grad_checkpointing(self, enable: bool = True):
        assert not enable, 'gradient checkpointing not supported'

    def get_classifier(self):
        return self.head

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            assert global_pool in ('', 'avg')
            self.global_pool = global_pool
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.head = _linear(self.num_features, num_classes, rngs=rngs, **self._dd) \
            if num_classes > 0 else None

    # -- forward -------------------------------------------------------------
    def _stage(self, x, i):
        """One stage: embed → blocks (PEG after block 0) → back to NHWC map."""
        B = x.shape[0]
        x, size = self.patch_embeds[i](x)
        x = self.pos_drops[i](x)
        for j, blk in enumerate(self.blocks[i]):
            x = blk(x, size)
            if j == 0:
                x = self.pos_block[i](x, size)
        if i < len(self.depths) - 1:
            x = x.reshape(B, *size, -1)
        return x, size

    def forward_features(self, x):
        for i in range(len(self.depths)):
            x, _ = self._stage(x, i)
        return self.norm(x) if self.norm is not None else x

    def forward_head(self, x, pre_logits: bool = False):
        if self.global_pool == 'avg':
            x = x.mean(axis=1)
        x = self.head_drop(x)
        if pre_logits or self.head is None:
            return x
        return self.head(x)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(self, x, indices=None, norm: bool = False,
                              stop_early: bool = False, output_fmt: str = 'NHWC',
                              intermediates_only: bool = False):
        assert output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        intermediates = []
        B = x.shape[0]
        last = len(self.depths) - 1
        for i in range(len(self.depths)):
            x, size = self._stage(x, i)
            if i in take_indices:
                if i == last:
                    feat = self.norm(x) if norm and self.norm is not None else x
                    intermediates.append(feat.reshape(B, *size, -1))
                else:
                    intermediates.append(x)
        if intermediates_only:
            return intermediates
        x = self.norm(x) if self.norm is not None else x
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, _ = feature_take_indices(len(self.blocks), indices)
        if prune_norm:
            self.norm = None
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict, model):
    from ._torch_convert import convert_torch_state_dict
    if 'model' in state_dict:
        state_dict = state_dict['model']
    return convert_torch_state_dict(state_dict, model)


def _create_twins(variant, pretrained=False, **kwargs):
    out_indices = kwargs.pop('out_indices', 4)
    return build_model_with_cfg(
        Twins, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=out_indices, feature_cls='getter'),
        **kwargs,
    )


def _cfg(url: str = '', **kwargs):
    return {
        'url': url,
        'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': None,
        'crop_pct': .9, 'interpolation': 'bicubic', 'fixed_input_size': True,
        'mean': IMAGENET_DEFAULT_MEAN, 'std': IMAGENET_DEFAULT_STD,
        'first_conv': 'patch_embeds.0.proj', 'classifier': 'head',
        'license': 'apache-2.0',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'twins_pcpvt_small.in1k': _cfg(),
    'twins_pcpvt_base.in1k': _cfg(),
    'twins_pcpvt_large.in1k': _cfg(),
    'twins_svt_small.in1k': _cfg(),
    'twins_svt_base.in1k': _cfg(),
    'twins_svt_large.in1k': _cfg(),
})


@register_model
def twins_pcpvt_small(pretrained=False, **kwargs) -> Twins:
    model_args = dict(
        patch_size=4, embed_dims=(64, 128, 320, 512), num_heads=(1, 2, 5, 8), mlp_ratios=(8, 8, 4, 4),
        depths=(3, 4, 6, 3), sr_ratios=(8, 4, 2, 1))
    return _create_twins('twins_pcpvt_small', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def twins_pcpvt_base(pretrained=False, **kwargs) -> Twins:
    model_args = dict(
        patch_size=4, embed_dims=(64, 128, 320, 512), num_heads=(1, 2, 5, 8), mlp_ratios=(8, 8, 4, 4),
        depths=(3, 4, 18, 3), sr_ratios=(8, 4, 2, 1))
    return _create_twins('twins_pcpvt_base', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def twins_pcpvt_large(pretrained=False, **kwargs) -> Twins:
    model_args = dict(
        patch_size=4, embed_dims=(64, 128, 320, 512), num_heads=(1, 2, 5, 8), mlp_ratios=(8, 8, 4, 4),
        depths=(3, 8, 27, 3), sr_ratios=(8, 4, 2, 1))
    return _create_twins('twins_pcpvt_large', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def twins_svt_small(pretrained=False, **kwargs) -> Twins:
    model_args = dict(
        patch_size=4, embed_dims=(64, 128, 256, 512), num_heads=(2, 4, 8, 16), mlp_ratios=(4, 4, 4, 4),
        depths=(2, 2, 10, 4), wss=(7, 7, 7, 7), sr_ratios=(8, 4, 2, 1))
    return _create_twins('twins_svt_small', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def twins_svt_base(pretrained=False, **kwargs) -> Twins:
    model_args = dict(
        patch_size=4, embed_dims=(96, 192, 384, 768), num_heads=(3, 6, 12, 24), mlp_ratios=(4, 4, 4, 4),
        depths=(2, 2, 18, 2), wss=(7, 7, 7, 7), sr_ratios=(8, 4, 2, 1))
    return _create_twins('twins_svt_base', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def twins_svt_large(pretrained=False, **kwargs) -> Twins:
    model_args = dict(
        patch_size=4, embed_dims=(128, 256, 512, 1024), num_heads=(4, 8, 16, 32), mlp_ratios=(4, 4, 4, 4),
        depths=(2, 2, 18, 2), wss=(7, 7, 7, 7), sr_ratios=(8, 4, 2, 1))
    return _create_twins('twins_svt_large', pretrained=pretrained, **dict(model_args, **kwargs))
