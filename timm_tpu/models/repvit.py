"""RepViT — mobile CNN revisited from a ViT perspective (NHWC / nnx).

Re-implements reference timm/models/repvit.py:1-693 (RepVit): a pure-conv
four-stage net whose blocks split token mixing (reparameterizable dw conv
branch sum) from channel mixing (1x1 conv MLP), with SE every other block and
an optional distillation head.

TPU notes: the train-time three-branch token mixer (dw kxk + dw 1x1 + id)
is kept un-fused — XLA fuses the branch adds into the BN epilogue anyway, and
keeping the branches preserves checkpoint round-tripping; all convs run NHWC
on the MXU. Inference-time structural fusion (reference repvit.py:53-71
``fuse()``) is a torch deploy-path optimization that XLA's constant folding
makes unnecessary here.
"""
from typing import Optional, Tuple

import jax.numpy as jnp
from flax import nnx

from timm_tpu.data.constants import IMAGENET_DEFAULT_MEAN, IMAGENET_DEFAULT_STD
from ..layers import BatchNorm2d, Dropout, SqueezeExcite, get_act_fn, to_ntuple
from ..layers.weight_init import trunc_normal_, zeros_
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._registry import generate_default_cfgs, register_model

__all__ = ['RepVit']


class ConvNorm(nnx.Module):
    """Conv (no bias, named ``c`` to match checkpoints) + BN
    (reference repvit.py:32-71)."""

    def __init__(self, in_dim, out_dim, ks=1, stride=1, pad=0, dilation=1, groups=1,
                 bn_weight_init=1.0, *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.c = nnx.Conv(
            in_dim, out_dim, kernel_size=(ks, ks), strides=stride,
            padding=[(pad, pad), (pad, pad)], kernel_dilation=(dilation, dilation),
            feature_group_count=groups, use_bias=False,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn = BatchNorm2d(out_dim, rngs=rngs)
        if bn_weight_init != 1.0:
            self.bn.scale[...] = jnp.full_like(self.bn.scale[...], bn_weight_init)

    def __call__(self, x):
        return self.bn(self.c(x))


class NormLinear(nnx.Module):
    """BN1d (named ``bn``) + Linear (named ``l``) classifier
    (reference repvit.py:74-105)."""

    def __init__(self, in_dim, out_dim, bias=True, std=0.02,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.bn = BatchNorm2d(in_dim, rngs=rngs)
        self.l = nnx.Linear(
            in_dim, out_dim, use_bias=bias, kernel_init=trunc_normal_(std=std),
            bias_init=zeros_, dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        B, C = x.shape
        x = self.bn(x.reshape(B, 1, 1, C)).reshape(B, C)
        return self.l(x)


class RepVggDw(nnx.Module):
    """Reparameterizable dw token mixer: dw kxk + dw 1x1 + identity, then BN
    (reference repvit.py:108-166). Legacy (m1/m2/m3) folds BN into each branch
    instead of applying one after the sum."""

    def __init__(self, ed, kernel_size, legacy=False,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.conv = ConvNorm(ed, ed, kernel_size, 1, (kernel_size - 1) // 2, groups=ed, **kw)
        self.legacy = legacy
        if legacy:
            self.conv1 = ConvNorm(ed, ed, 1, 1, 0, groups=ed, **kw)
            self.bn = None
        else:
            self.conv1 = nnx.Conv(
                ed, ed, kernel_size=(1, 1), strides=1, padding='VALID',
                feature_group_count=ed, use_bias=True,
                dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            self.bn = BatchNorm2d(ed, rngs=rngs)

    def __call__(self, x):
        x = self.conv(x) + self.conv1(x) + x
        if self.bn is not None:
            x = self.bn(x)
        return x


class RepVitMlp(nnx.Module):
    """1x1 conv MLP channel mixer (reference repvit.py:169-186)."""

    def __init__(self, in_dim, hidden_dim, act_layer,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.conv1 = ConvNorm(in_dim, hidden_dim, 1, 1, 0, **kw)
        self.act = get_act_fn(act_layer)
        self.conv2 = ConvNorm(hidden_dim, in_dim, 1, 1, 0, bn_weight_init=0.0, **kw)

    def __call__(self, x):
        return self.conv2(self.act(self.conv1(x)))


class RepViTBlock(nnx.Module):
    """Token mixer + optional SE + residual channel mixer
    (reference repvit.py:189-212)."""

    def __init__(self, in_dim, mlp_ratio, kernel_size, use_se, act_layer, legacy=False,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.token_mixer = RepVggDw(in_dim, kernel_size, legacy, **kw)
        self.se = SqueezeExcite(in_dim, 0.25, **kw) if use_se else None
        self.channel_mixer = RepVitMlp(in_dim, int(in_dim * mlp_ratio), act_layer, **kw)

    def __call__(self, x):
        x = self.token_mixer(x)
        if self.se is not None:
            x = self.se(x)
        return x + self.channel_mixer(x)


class RepVitStem(nnx.Module):
    """Two strided 3x3 ConvNorms, stride 4 total (reference repvit.py:215-232)."""

    def __init__(self, in_chs, out_chs, act_layer,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.conv1 = ConvNorm(in_chs, out_chs // 2, 3, 2, 1, **kw)
        self.act1 = get_act_fn(act_layer)
        self.conv2 = ConvNorm(out_chs // 2, out_chs, 3, 2, 1, **kw)
        self.stride = 4

    def __call__(self, x):
        return self.conv2(self.act1(self.conv1(x)))


class RepVitDownsample(nnx.Module):
    """Pre-block + dw spatial downsample + 1x1 channel change + residual FFN
    (reference repvit.py:235-278)."""

    def __init__(self, in_dim, mlp_ratio, out_dim, kernel_size, act_layer, legacy=False,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.pre_block = RepViTBlock(in_dim, mlp_ratio, kernel_size, use_se=False,
                                     act_layer=act_layer, legacy=legacy, **kw)
        self.spatial_downsample = ConvNorm(
            in_dim, in_dim, kernel_size, 2, (kernel_size - 1) // 2, groups=in_dim, **kw)
        self.channel_downsample = ConvNorm(in_dim, out_dim, 1, 1, **kw)
        self.ffn = RepVitMlp(out_dim, int(out_dim * mlp_ratio), act_layer, **kw)

    def __call__(self, x):
        x = self.pre_block(x)
        x = self.spatial_downsample(x)
        x = self.channel_downsample(x)
        return x + self.ffn(x)


class RepVitClassifier(nnx.Module):
    """Dropout + NormLinear head, optionally distilled: eval averages the two
    heads, distilled training returns both (reference repvit.py:281-326)."""

    def __init__(self, dim, num_classes, distillation=False, drop=0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.head_drop = Dropout(drop, rngs=rngs)
        self.head = NormLinear(dim, num_classes, **kw) if num_classes > 0 else None
        self.distillation = distillation
        self.distilled_training = False
        self.num_classes = num_classes
        self.head_dist = NormLinear(dim, num_classes, **kw) if (distillation and num_classes > 0) else None

    def __call__(self, x):
        x = self.head_drop(x)
        if self.head is None:
            return x
        if self.distillation:
            x1, x2 = self.head(x), self.head_dist(x)
            if self.distilled_training and not self.head_drop.deterministic:
                return x1, x2
            return (x1 + x2) / 2
        return self.head(x)


class RepVitStage(nnx.Module):
    """Optional downsample + depth blocks with SE on alternating blocks
    (reference repvit.py:329-370)."""

    def __init__(self, in_dim, out_dim, depth, mlp_ratio, act_layer, kernel_size=3,
                 downsample=True, legacy=False,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        if downsample:
            self.downsample = RepVitDownsample(
                in_dim, mlp_ratio, out_dim, kernel_size, act_layer, legacy, **kw)
        else:
            assert in_dim == out_dim
            self.downsample = None
        blocks = []
        use_se = True
        for _ in range(depth):
            blocks.append(RepViTBlock(out_dim, mlp_ratio, kernel_size, use_se, act_layer, legacy, **kw))
            use_se = not use_se
        self.blocks = nnx.List(blocks)
        self.grad_checkpointing = False

    def __call__(self, x):
        if self.downsample is not None:
            x = self.downsample(x)
        remat_blk = nnx.remat(RepViTBlock.__call__) if self.grad_checkpointing else None
        for blk in self.blocks:
            x = remat_blk(blk, x) if remat_blk is not None else blk(x)
        return x


class RepVit(nnx.Module):
    """RepViT (reference repvit.py:373-546)."""

    def __init__(
            self,
            in_chans: int = 3,
            img_size: int = 224,
            embed_dim: Tuple[int, ...] = (48,),
            depth: Tuple[int, ...] = (2,),
            mlp_ratio: float = 2,
            global_pool: str = 'avg',
            kernel_size: int = 3,
            num_classes: int = 1000,
            act_layer='gelu',
            distillation: bool = True,
            drop_rate: float = 0.0,
            legacy: bool = False,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: Optional[nnx.Rngs] = None,
    ):
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.global_pool = global_pool
        self.embed_dim = embed_dim
        self.num_classes = num_classes
        self.drop_rate = drop_rate
        self.distillation = distillation
        self._dd = dict(dtype=dtype, param_dtype=param_dtype)

        in_dim = embed_dim[0]
        self.stem = RepVitStem(in_chans, in_dim, act_layer, **kw)
        stride = self.stem.stride
        num_stages = len(embed_dim)
        mlp_ratios = to_ntuple(num_stages)(mlp_ratio)

        self.feature_info = []
        stages = []
        for i in range(num_stages):
            downsample = i != 0
            stages.append(RepVitStage(
                in_dim, embed_dim[i], depth[i], mlp_ratio=mlp_ratios[i],
                act_layer=act_layer, kernel_size=kernel_size,
                downsample=downsample, legacy=legacy, **kw))
            stride *= 2 if downsample else 1
            self.feature_info += [dict(num_chs=embed_dim[i], reduction=stride, module=f'stages.{i}')]
            in_dim = embed_dim[i]
        self.stages = nnx.List(stages)

        self.num_features = self.head_hidden_size = embed_dim[-1]
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        self.head = RepVitClassifier(embed_dim[-1], num_classes, distillation, **kw)

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return set()

    def group_matcher(self, coarse: bool = False):
        return dict(stem=r'^stem', blocks=[(r'^stages\.(\d+)', None)])

    def set_grad_checkpointing(self, enable: bool = True):
        for s in self.stages:
            s.grad_checkpointing = enable

    def set_distilled_training(self, enable: bool = True):
        self.head.distilled_training = enable

    def get_classifier(self):
        return self.head

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None,
                         distillation: bool = False, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            self.global_pool = global_pool
        self.head = RepVitClassifier(
            self.embed_dim[-1], num_classes, distillation,
            rngs=rngs if rngs is not None else nnx.Rngs(0), **self._dd)

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        x = self.stem(x)
        for stage in self.stages:
            x = stage(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        if self.global_pool == 'avg':
            x = x.mean(axis=(1, 2))
        x = self.head_drop(x)
        if pre_logits:
            return x
        return self.head(x)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(self, x, indices=None, norm: bool = False,
                              stop_early: bool = False, output_fmt: str = 'NHWC',
                              intermediates_only: bool = False):
        assert output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        intermediates = []
        x = self.stem(x)
        stages = self.stages if not stop_early else self.stages[:max_index + 1]
        for feat_idx, stage in enumerate(stages):
            x = stage(x)
            if feat_idx in take_indices:
                intermediates.append(x)
        if intermediates_only:
            return intermediates
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        self.stages = nnx.List(list(self.stages)[:max_index + 1])
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict, model):
    from ._torch_convert import convert_torch_state_dict
    if 'model' in state_dict:
        state_dict = state_dict['model']
    return convert_torch_state_dict(state_dict, model)


def _cfg(url: str = '', **kwargs):
    return {
        'url': url, 'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': (7, 7),
        'crop_pct': 0.95, 'interpolation': 'bicubic',
        'mean': IMAGENET_DEFAULT_MEAN, 'std': IMAGENET_DEFAULT_STD,
        'first_conv': 'stem.conv1.c', 'classifier': ('head.head.l', 'head.head_dist.l'),
        'license': 'apache-2.0',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'repvit_m1.dist_in1k': _cfg(),
    'repvit_m2.dist_in1k': _cfg(),
    'repvit_m3.dist_in1k': _cfg(),
    'repvit_m0_9.dist_300e_in1k': _cfg(),
    'repvit_m0_9.dist_450e_in1k': _cfg(),
    'repvit_m1_0.dist_300e_in1k': _cfg(),
    'repvit_m1_0.dist_450e_in1k': _cfg(),
    'repvit_m1_1.dist_300e_in1k': _cfg(),
    'repvit_m1_1.dist_450e_in1k': _cfg(),
    'repvit_m1_5.dist_300e_in1k': _cfg(),
    'repvit_m1_5.dist_450e_in1k': _cfg(),
    'repvit_m2_3.dist_300e_in1k': _cfg(),
    'repvit_m2_3.dist_450e_in1k': _cfg(),
})


def _create_repvit(variant, pretrained=False, **kwargs):
    out_indices = kwargs.pop('out_indices', (0, 1, 2, 3))
    return build_model_with_cfg(
        RepVit, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=out_indices, feature_cls='getter'),
        **kwargs,
    )


@register_model
def repvit_m1(pretrained=False, **kwargs):
    model_args = dict(embed_dim=(48, 96, 192, 384), depth=(2, 2, 14, 2), legacy=True)
    return _create_repvit('repvit_m1', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def repvit_m2(pretrained=False, **kwargs):
    model_args = dict(embed_dim=(64, 128, 256, 512), depth=(2, 2, 12, 2), legacy=True)
    return _create_repvit('repvit_m2', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def repvit_m3(pretrained=False, **kwargs):
    model_args = dict(embed_dim=(64, 128, 256, 512), depth=(4, 4, 18, 2), legacy=True)
    return _create_repvit('repvit_m3', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def repvit_m0_9(pretrained=False, **kwargs):
    model_args = dict(embed_dim=(48, 96, 192, 384), depth=(2, 2, 14, 2))
    return _create_repvit('repvit_m0_9', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def repvit_m1_0(pretrained=False, **kwargs):
    model_args = dict(embed_dim=(56, 112, 224, 448), depth=(2, 2, 14, 2))
    return _create_repvit('repvit_m1_0', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def repvit_m1_1(pretrained=False, **kwargs):
    model_args = dict(embed_dim=(64, 128, 256, 512), depth=(2, 2, 12, 2))
    return _create_repvit('repvit_m1_1', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def repvit_m1_5(pretrained=False, **kwargs):
    model_args = dict(embed_dim=(64, 128, 256, 512), depth=(4, 4, 24, 4))
    return _create_repvit('repvit_m1_5', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def repvit_m2_3(pretrained=False, **kwargs):
    model_args = dict(embed_dim=(80, 160, 320, 640), depth=(6, 6, 34, 2))
    return _create_repvit('repvit_m2_3', pretrained=pretrained, **dict(model_args, **kwargs))
