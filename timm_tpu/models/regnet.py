"""RegNet X/Y (reference: timm/models/regnet.py:1-1490), TPU-native NHWC.

Widths/depths from the RegNet linear log-space parameterization; Y variants
add SE. Bottleneck blocks with group conv reuse the conv/norm-act stack.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Union

import numpy as np
import jax.numpy as jnp
from flax import nnx

from ..layers import BatchNormAct2d, ClassifierHead, DropPath, SEModule, create_conv2d, get_act_fn
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._manipulate import (
    BlockStackError, checkpoint_seq, resolve_stage_scan, scan_stage_stack,
    warn_scan_fallback,
)
from ._registry import generate_default_cfgs, register_model

__all__ = ['RegNet']


def generate_regnet_widths(width_slope: float, width_initial: int, width_mult: float, depth: int,
                           group_size: int, quant: int = 8):
    """Per-stage (widths, depths) from the RegNet parameterization
    (reference regnet.py generate_regnet)."""
    widths_cont = np.arange(depth) * width_slope + width_initial
    width_exps = np.round(np.log(widths_cont / width_initial) / np.log(width_mult))
    widths = width_initial * np.power(width_mult, width_exps)
    widths = np.round(np.divide(widths, quant)) * quant
    num_stages = len(np.unique(widths))
    widths = widths.astype(int)
    # adjust for group divisibility
    stage_widths, stage_depths = np.unique(widths, return_counts=True)
    stage_widths = [int(round(w / group_size) * group_size) or group_size for w in stage_widths]
    return list(stage_widths), list(stage_depths.astype(int)), num_stages


class RegNetBottleneck(nnx.Module):
    def __init__(
            self,
            in_chs: int,
            out_chs: int,
            stride: int = 1,
            group_size: int = 1,
            bottle_ratio: float = 1.0,
            se_ratio: float = 0.0,
            act_layer: Union[str, Callable] = 'relu',
            norm_layer: Callable = BatchNormAct2d,
            drop_path: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        bottleneck_chs = int(round(out_chs * bottle_ratio))
        groups = max(1, bottleneck_chs // group_size)

        self.conv1 = create_conv2d(in_chs, bottleneck_chs, 1, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn1 = norm_layer(bottleneck_chs, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.conv2 = create_conv2d(
            bottleneck_chs, bottleneck_chs, 3, stride=stride, groups=groups,
            padding=None, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn2 = norm_layer(bottleneck_chs, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.se = SEModule(
            bottleneck_chs, rd_channels=int(round(in_chs * se_ratio)), act_layer=act_layer,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs) if se_ratio > 0 else None
        self.conv3 = create_conv2d(bottleneck_chs, out_chs, 1, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn3 = norm_layer(out_chs, apply_act=False, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.act = get_act_fn(act_layer)
        self.drop_path = DropPath(drop_path, rngs=rngs)

        if in_chs != out_chs or stride != 1:
            self.downsample_conv = create_conv2d(
                in_chs, out_chs, 1, stride=stride, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            self.downsample_bn = norm_layer(
                out_chs, apply_act=False, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        else:
            self.downsample_conv = None
            self.downsample_bn = None

    def __call__(self, x):
        shortcut = x
        x = self.bn1(self.conv1(x))
        x = self.bn2(self.conv2(x))
        if self.se is not None:
            x = self.se(x)
        x = self.bn3(self.conv3(x))
        x = self.drop_path(x)
        if self.downsample_conv is not None:
            shortcut = self.downsample_bn(self.downsample_conv(shortcut))
        return self.act(x + shortcut)


class RegNet(nnx.Module):
    def __init__(
            self,
            cfg: Dict[str, Any],
            in_chans: int = 3,
            num_classes: int = 1000,
            output_stride: int = 32,
            global_pool: str = 'avg',
            drop_rate: float = 0.0,
            drop_path_rate: float = 0.0,
            act_layer: Union[str, Callable] = 'relu',
            norm_layer: Callable = BatchNormAct2d,
            stage_scan: Optional[bool] = None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert output_stride == 32
        self.num_classes = num_classes
        self.drop_rate = drop_rate

        stem_width = cfg.get('stem_width', 32)
        self.stem_conv = create_conv2d(
            in_chans, stem_width, 3, stride=2, padding=None,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.stem_bn = norm_layer(stem_width, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.feature_info = [dict(num_chs=stem_width, reduction=2, module='stem_bn')]

        widths, depths, _ = generate_regnet_widths(
            cfg['wa'], cfg['w0'], cfg['wm'], cfg['depth'], cfg['group_size'])
        se_ratio = cfg.get('se_ratio', 0.0)
        bottle_ratio = cfg.get('bottle_ratio', 1.0)

        total_blocks = sum(depths)
        block_idx = 0
        prev_chs = stem_width
        stride_total = 2
        stages = []
        for si, (w, d) in enumerate(zip(widths, depths)):
            blocks = []
            for bi in range(d):
                stride = 2 if bi == 0 else 1
                dpr = drop_path_rate * block_idx / max(total_blocks - 1, 1)
                blocks.append(RegNetBottleneck(
                    prev_chs, w, stride=stride,
                    group_size=cfg['group_size'],
                    bottle_ratio=bottle_ratio,
                    se_ratio=se_ratio,
                    act_layer=act_layer,
                    norm_layer=norm_layer,
                    drop_path=dpr,
                    dtype=dtype, param_dtype=param_dtype, rngs=rngs))
                prev_chs = w
                block_idx += 1
            stride_total *= 2
            stages.append(nnx.List(blocks))
            self.feature_info.append(dict(num_chs=w, reduction=stride_total, module=f's{si + 1}'))
        self.stages = nnx.List(stages)

        self.num_features = self.head_hidden_size = prev_chs
        self.head = ClassifierHead(
            prev_chs, num_classes, pool_type=global_pool, drop_rate=drop_rate,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.grad_checkpointing = False
        self.stage_scan = resolve_stage_scan(stage_scan)

    def no_weight_decay(self) -> set:
        return set()

    def group_matcher(self, coarse: bool = False):
        return dict(stem=r'^stem_', blocks=r'^stages\.(\d+)' if coarse else r'^stages\.(\d+)\.(\d+)')

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def set_stage_scan(self, enable: bool = True):
        # regnet has no Stage module; forward_features scans each block list.
        # BatchNorm running stats gate scan to eval mode (loud loop fallback
        # in train mode), so the flag is safe to leave on.
        self.stage_scan = enable

    # stage scan IS this family's scan-over-layers: generic machinery that
    # toggles `set_block_scan` (bench replay, probes) reaches it too
    set_block_scan = set_stage_scan

    def get_classifier(self):
        return self.head.fc

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        self.head.reset(num_classes, pool_type=global_pool, rngs=rngs)

    def forward_features(self, x):
        x = self.stem_bn(self.stem_conv(x))
        for stage in self.stages:
            if self.stage_scan:
                try:
                    x = scan_stage_stack(stage, x, remat=self.grad_checkpointing)
                    continue
                except BlockStackError as e:
                    warn_scan_fallback(type(self).__name__, e, what='stage_scan')
            if self.grad_checkpointing:
                x = checkpoint_seq(stage, x)
            else:
                for b in stage:
                    x = b(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        return self.head(x, pre_logits=pre_logits)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.stages) + 1, indices)
        x = self.stem_bn(self.stem_conv(x))
        intermediates = []
        if 0 in take_indices:
            intermediates.append(x)
        for i, stage in enumerate(self.stages):
            if stop_early and i > max_index - 1:
                break
            for b in stage:
                x = b(x)
            if (i + 1) in take_indices:
                intermediates.append(x)
        if intermediates_only:
            return intermediates
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, _ = feature_take_indices(len(self.stages) + 1, indices)
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


# RegNet parameterizations (reference regnet.py model_cfgs)
_model_cfgs = dict(
    regnetx_002=dict(w0=24, wa=36.44, wm=2.49, group_size=8, depth=13),
    regnetx_004=dict(w0=24, wa=24.48, wm=2.54, group_size=16, depth=22),
    regnetx_008=dict(w0=56, wa=35.73, wm=2.28, group_size=16, depth=16),
    regnetx_016=dict(w0=80, wa=34.01, wm=2.25, group_size=24, depth=18),
    regnetx_032=dict(w0=88, wa=26.31, wm=2.25, group_size=48, depth=25),
    regnety_002=dict(w0=24, wa=36.44, wm=2.49, group_size=8, depth=13, se_ratio=0.25),
    regnety_004=dict(w0=48, wa=27.89, wm=2.09, group_size=8, depth=16, se_ratio=0.25),
    regnety_008=dict(w0=56, wa=38.84, wm=2.4, group_size=16, depth=14, se_ratio=0.25),
    regnety_016=dict(w0=48, wa=20.71, wm=2.65, group_size=24, depth=27, se_ratio=0.25),
    regnety_032=dict(w0=80, wa=42.63, wm=2.66, group_size=24, depth=21, se_ratio=0.25),
)


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url, 'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': (7, 7),
        'crop_pct': 0.875, 'interpolation': 'bicubic',
        'mean': (0.485, 0.456, 0.406), 'std': (0.229, 0.224, 0.225),
        'first_conv': 'stem_conv', 'classifier': 'head.fc',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'test_regnet.untrained': _cfg(input_size=(3, 160, 160)),
    'regnetx_002.pycls_in1k': _cfg(hf_hub_id='timm/'),
    'regnetx_004.pycls_in1k': _cfg(hf_hub_id='timm/'),
    'regnetx_008.pycls_in1k': _cfg(hf_hub_id='timm/'),
    'regnetx_032.pycls_in1k': _cfg(hf_hub_id='timm/'),
    'regnety_004.pycls_in1k': _cfg(hf_hub_id='timm/'),
    'regnety_008.pycls_in1k': _cfg(hf_hub_id='timm/'),
    'regnetx_016.pycls_in1k': _cfg(hf_hub_id='timm/'),
    'regnety_002.pycls_in1k': _cfg(hf_hub_id='timm/'),
    'regnety_016.tv2_in1k': _cfg(hf_hub_id='timm/'),
    'regnety_032.ra_in1k': _cfg(hf_hub_id='timm/', crop_pct=0.95),
})


def checkpoint_filter_fn(state_dict, model):
    """Map reference regnet names (stem.conv/bn, s1..s4 stages, b1.. blocks,
    SE fc1/fc2) → this layout."""
    import re
    from ._torch_convert import convert_torch_state_dict
    out = {}
    for k, v in state_dict.items():
        k = re.sub(r'^stem\.conv\.', 'stem_conv.', k)
        k = re.sub(r'^stem\.bn\.', 'stem_bn.', k)
        m = re.match(r'^s(\d+)\.b(\d+)\.(.*)$', k)
        if m:
            rest = m.group(3)
            rest = rest.replace('downsample.conv.', 'downsample_conv.')
            rest = rest.replace('downsample.bn.', 'downsample_bn.')
            rest = re.sub(r'^conv(\d)\.conv\.', r'conv\1.', rest)
            rest = re.sub(r'^conv(\d)\.bn\.', r'bn\1.', rest)
            k = f'stages.{int(m.group(1)) - 1}.{int(m.group(2)) - 1}.{rest}'
        out[k] = v
    return convert_torch_state_dict(out, model)


def _create_regnet(variant: str, pretrained: bool = False, **kwargs) -> RegNet:
    return build_model_with_cfg(
        RegNet, variant, pretrained,
        model_cfg=_model_cfgs[variant],
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=(0, 1, 2, 3, 4)),
        **kwargs,
    )


@register_model
def regnetx_002(pretrained=False, **kwargs) -> RegNet:
    return _create_regnet('regnetx_002', pretrained, **kwargs)


@register_model
def regnetx_004(pretrained=False, **kwargs) -> RegNet:
    return _create_regnet('regnetx_004', pretrained, **kwargs)


@register_model
def regnetx_008(pretrained=False, **kwargs) -> RegNet:
    return _create_regnet('regnetx_008', pretrained, **kwargs)


@register_model
def regnetx_016(pretrained=False, **kwargs) -> RegNet:
    return _create_regnet('regnetx_016', pretrained, **kwargs)


@register_model
def regnetx_032(pretrained=False, **kwargs) -> RegNet:
    return _create_regnet('regnetx_032', pretrained, **kwargs)


@register_model
def regnety_004(pretrained=False, **kwargs) -> RegNet:
    return _create_regnet('regnety_004', pretrained, **kwargs)


@register_model
def regnety_008(pretrained=False, **kwargs) -> RegNet:
    return _create_regnet('regnety_008', pretrained, **kwargs)


@register_model
def regnety_002(pretrained=False, **kwargs) -> RegNet:
    return _create_regnet('regnety_002', pretrained, **kwargs)


@register_model
def regnety_016(pretrained=False, **kwargs) -> RegNet:
    return _create_regnet('regnety_016', pretrained, **kwargs)


@register_model
def regnety_032(pretrained=False, **kwargs) -> RegNet:
    return _create_regnet('regnety_032', pretrained, **kwargs)


@register_model
def test_regnet(pretrained=False, **kwargs) -> RegNet:
    """Tiny fixture for the default test sweeps."""
    cfg = dict(w0=24, wa=24.0, wm=2.5, group_size=8, depth=4, se_ratio=0.25, stem_width=16)
    return build_model_with_cfg(
        RegNet, 'test_regnet', pretrained,
        model_cfg=cfg,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=(0, 1, 2)),
        **kwargs,
    )
