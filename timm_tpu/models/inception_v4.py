"""Inception-V4 (reference: timm/models/inception_v4.py:1-445), TPU-native
NHWC.

Multi-branch inception cells with asymmetric (1x7 / 7x1) convs; all branch
concats are channel-axis (last) in NHWC, so they are free layout ops.
"""
from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple, Union

import jax.numpy as jnp
from flax import nnx

from ..layers import BatchNormAct2d, ConvNormAct, Pool2d, SelectAdaptivePool2d, trunc_normal_, zeros_
from ..layers.drop import Dropout
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._registry import generate_default_cfgs, register_model

__all__ = ['InceptionV4']


class Mixed3a(nnx.Module):
    def __init__(self, conv_block, **kw):
        self.maxpool = Pool2d('max', 3, 2, padding=0)
        self.conv = conv_block(64, 96, kernel_size=3, stride=2, **kw)

    def __call__(self, x):
        return jnp.concatenate([self.maxpool(x), self.conv(x)], axis=-1)


class Mixed4a(nnx.Module):
    def __init__(self, conv_block, **kw):
        self.branch0 = nnx.List([
            conv_block(160, 64, kernel_size=1, stride=1, **kw),
            conv_block(64, 96, kernel_size=3, stride=1, **kw),
        ])
        self.branch1 = nnx.List([
            conv_block(160, 64, kernel_size=1, stride=1, **kw),
            conv_block(64, 64, kernel_size=(1, 7), stride=1, padding=(0, 3), **kw),
            conv_block(64, 64, kernel_size=(7, 1), stride=1, padding=(3, 0), **kw),
            conv_block(64, 96, kernel_size=(3, 3), stride=1, **kw),
        ])

    def __call__(self, x):
        x0 = x
        for m in self.branch0:
            x0 = m(x0)
        x1 = x
        for m in self.branch1:
            x1 = m(x1)
        return jnp.concatenate([x0, x1], axis=-1)


class Mixed5a(nnx.Module):
    def __init__(self, conv_block, **kw):
        self.conv = conv_block(192, 192, kernel_size=3, stride=2, **kw)
        self.maxpool = Pool2d('max', 3, 2, padding=0)

    def __call__(self, x):
        return jnp.concatenate([self.conv(x), self.maxpool(x)], axis=-1)


def _seq(mods):
    def run(x):
        for m in mods:
            x = m(x)
        return x
    return run


class InceptionA(nnx.Module):
    def __init__(self, conv_block, **kw):
        self.branch0 = conv_block(384, 96, kernel_size=1, stride=1, **kw)
        self.branch1 = nnx.List([
            conv_block(384, 64, kernel_size=1, stride=1, **kw),
            conv_block(64, 96, kernel_size=3, stride=1, padding=1, **kw),
        ])
        self.branch2 = nnx.List([
            conv_block(384, 64, kernel_size=1, stride=1, **kw),
            conv_block(64, 96, kernel_size=3, stride=1, padding=1, **kw),
            conv_block(96, 96, kernel_size=3, stride=1, padding=1, **kw),
        ])
        # torch Sequential(AvgPool, conv) → conv at index 1
        self.branch3 = nnx.List([conv_block(384, 96, kernel_size=1, stride=1, **kw)])
        self._pool = Pool2d('avg', 3, 1, padding=1)

    def __call__(self, x):
        return jnp.concatenate([
            self.branch0(x), _seq(self.branch1)(x), _seq(self.branch2)(x),
            self.branch3[0](self._pool(x)),
        ], axis=-1)


class ReductionA(nnx.Module):
    def __init__(self, conv_block, **kw):
        self.branch0 = conv_block(384, 384, kernel_size=3, stride=2, **kw)
        self.branch1 = nnx.List([
            conv_block(384, 192, kernel_size=1, stride=1, **kw),
            conv_block(192, 224, kernel_size=3, stride=1, padding=1, **kw),
            conv_block(224, 256, kernel_size=3, stride=2, **kw),
        ])
        self._pool = Pool2d('max', 3, 2, padding=0)

    def __call__(self, x):
        return jnp.concatenate([self.branch0(x), _seq(self.branch1)(x), self._pool(x)], axis=-1)


class InceptionB(nnx.Module):
    def __init__(self, conv_block, **kw):
        self.branch0 = conv_block(1024, 384, kernel_size=1, stride=1, **kw)
        self.branch1 = nnx.List([
            conv_block(1024, 192, kernel_size=1, stride=1, **kw),
            conv_block(192, 224, kernel_size=(1, 7), stride=1, padding=(0, 3), **kw),
            conv_block(224, 256, kernel_size=(7, 1), stride=1, padding=(3, 0), **kw),
        ])
        self.branch2 = nnx.List([
            conv_block(1024, 192, kernel_size=1, stride=1, **kw),
            conv_block(192, 192, kernel_size=(7, 1), stride=1, padding=(3, 0), **kw),
            conv_block(192, 224, kernel_size=(1, 7), stride=1, padding=(0, 3), **kw),
            conv_block(224, 224, kernel_size=(7, 1), stride=1, padding=(3, 0), **kw),
            conv_block(224, 256, kernel_size=(1, 7), stride=1, padding=(0, 3), **kw),
        ])
        self.branch3 = nnx.List([conv_block(1024, 128, kernel_size=1, stride=1, **kw)])
        self._pool = Pool2d('avg', 3, 1, padding=1)

    def __call__(self, x):
        return jnp.concatenate([
            self.branch0(x), _seq(self.branch1)(x), _seq(self.branch2)(x),
            self.branch3[0](self._pool(x)),
        ], axis=-1)


class ReductionB(nnx.Module):
    def __init__(self, conv_block, **kw):
        self.branch0 = nnx.List([
            conv_block(1024, 192, kernel_size=1, stride=1, **kw),
            conv_block(192, 192, kernel_size=3, stride=2, **kw),
        ])
        self.branch1 = nnx.List([
            conv_block(1024, 256, kernel_size=1, stride=1, **kw),
            conv_block(256, 256, kernel_size=(1, 7), stride=1, padding=(0, 3), **kw),
            conv_block(256, 320, kernel_size=(7, 1), stride=1, padding=(3, 0), **kw),
            conv_block(320, 320, kernel_size=3, stride=2, **kw),
        ])
        self._pool = Pool2d('max', 3, 2, padding=0)

    def __call__(self, x):
        return jnp.concatenate([_seq(self.branch0)(x), _seq(self.branch1)(x), self._pool(x)], axis=-1)


class InceptionC(nnx.Module):
    def __init__(self, conv_block, **kw):
        self.branch0 = conv_block(1536, 256, kernel_size=1, stride=1, **kw)
        self.branch1_0 = conv_block(1536, 384, kernel_size=1, stride=1, **kw)
        self.branch1_1a = conv_block(384, 256, kernel_size=(1, 3), stride=1, padding=(0, 1), **kw)
        self.branch1_1b = conv_block(384, 256, kernel_size=(3, 1), stride=1, padding=(1, 0), **kw)
        self.branch2_0 = conv_block(1536, 384, kernel_size=1, stride=1, **kw)
        self.branch2_1 = conv_block(384, 448, kernel_size=(3, 1), stride=1, padding=(1, 0), **kw)
        self.branch2_2 = conv_block(448, 512, kernel_size=(1, 3), stride=1, padding=(0, 1), **kw)
        self.branch2_3a = conv_block(512, 256, kernel_size=(1, 3), stride=1, padding=(0, 1), **kw)
        self.branch2_3b = conv_block(512, 256, kernel_size=(3, 1), stride=1, padding=(1, 0), **kw)
        self.branch3 = nnx.List([conv_block(1536, 256, kernel_size=1, stride=1, **kw)])
        self._pool = Pool2d('avg', 3, 1, padding=1)

    def __call__(self, x):
        x0 = self.branch0(x)
        x1_0 = self.branch1_0(x)
        x1 = jnp.concatenate([self.branch1_1a(x1_0), self.branch1_1b(x1_0)], axis=-1)
        x2 = self.branch2_2(self.branch2_1(self.branch2_0(x)))
        x2 = jnp.concatenate([self.branch2_3a(x2), self.branch2_3b(x2)], axis=-1)
        x3 = self.branch3[0](self._pool(x))
        return jnp.concatenate([x0, x1, x2, x3], axis=-1)


class InceptionV4(nnx.Module):
    """(reference inception_v4.py:220-420)."""

    def __init__(
            self,
            num_classes: int = 1000,
            in_chans: int = 3,
            output_stride: int = 32,
            drop_rate: float = 0.0,
            global_pool: str = 'avg',
            norm_eps: float = 1e-3,
            act_layer: str = 'relu',
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert output_stride == 32
        self.num_classes = num_classes
        self.drop_rate = drop_rate
        self.num_features = self.head_hidden_size = 1536
        conv_block = partial(
            ConvNormAct, padding=0,
            norm_layer=partial(BatchNormAct2d, eps=norm_eps),
            act_layer=act_layer)
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        features = [
            conv_block(in_chans, 32, kernel_size=3, stride=2, **kw),
            conv_block(32, 32, kernel_size=3, stride=1, **kw),
            conv_block(32, 64, kernel_size=3, stride=1, padding=1, **kw),
            Mixed3a(conv_block, **kw),
            Mixed4a(conv_block, **kw),
            Mixed5a(conv_block, **kw),
        ]
        features += [InceptionA(conv_block, **kw) for _ in range(4)]
        features += [ReductionA(conv_block, **kw)]
        features += [InceptionB(conv_block, **kw) for _ in range(7)]
        features += [ReductionB(conv_block, **kw)]
        features += [InceptionC(conv_block, **kw) for _ in range(3)]
        self.features = nnx.List(features)
        self.feature_info = [
            dict(num_chs=64, reduction=2, module='features.2'),
            dict(num_chs=160, reduction=4, module='features.3'),
            dict(num_chs=384, reduction=8, module='features.9'),
            dict(num_chs=1024, reduction=16, module='features.17'),
            dict(num_chs=1536, reduction=32, module='features.21'),
        ]
        self.global_pool = SelectAdaptivePool2d(pool_type=global_pool, flatten=True)
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        self.last_linear = nnx.Linear(
            self.num_features, num_classes, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs) if num_classes > 0 else None
        self._dtype = dtype
        self._param_dtype = param_dtype

    def no_weight_decay(self) -> set:
        return set()

    def group_matcher(self, coarse: bool = False):
        return dict(stem=r'^features\.[012]\.', blocks=r'^features\.(\d+)')

    def set_grad_checkpointing(self, enable: bool = True):
        assert not enable, 'gradient checkpointing not supported'

    def get_classifier(self):
        return self.last_linear

    def reset_classifier(self, num_classes: int, global_pool: str = 'avg', *, rngs=None):
        self.num_classes = num_classes
        self.global_pool = SelectAdaptivePool2d(pool_type=global_pool, flatten=True)
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.last_linear = nnx.Linear(
            self.num_features, num_classes, kernel_init=trunc_normal_(std=0.02),
            dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs) if num_classes > 0 else None

    def forward_features(self, x):
        for m in self.features:
            x = m(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        x = self.global_pool(x)
        x = self.head_drop(x)
        if pre_logits or self.last_linear is None:
            return x
        return self.last_linear(x)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt == 'NHWC'
        stage_ends = [int(info['module'].split('.')[-1]) for info in self.feature_info]
        take_indices, max_index = feature_take_indices(len(stage_ends), indices)
        take_indices = [stage_ends[i] for i in take_indices]
        max_index = stage_ends[max_index]
        intermediates = []
        feats = self.features if not stop_early else list(self.features)[:max_index + 1]
        for feat_idx, m in enumerate(feats):
            x = m(x)
            if feat_idx in take_indices:
                intermediates.append(x)
        if intermediates_only:
            return intermediates
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        stage_ends = [int(info['module'].split('.')[-1]) for info in self.feature_info]
        take_indices, max_index = feature_take_indices(len(stage_ends), indices)
        max_index = stage_ends[max_index]
        self.features = nnx.List(list(self.features)[:max_index + 1])
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict, model):
    """Branch Sequentials containing paramless pools: the conv inside
    branch3 sits at torch index 1 but our list index 0."""
    import re

    from ._torch_convert import convert_torch_state_dict
    out = {}
    for k, v in state_dict.items():
        k = re.sub(r'\.branch3\.1\.', '.branch3.0.', k)
        out[k] = v
    return convert_torch_state_dict(out, model)


def _create_inception_v4(variant, pretrained=False, **kwargs) -> InceptionV4:
    return build_model_with_cfg(
        InceptionV4, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(feature_cls='getter'),
        **kwargs,
    )


default_cfgs = generate_default_cfgs({
    'inception_v4.tf_in1k': {
        'hf_hub_id': 'timm/',
        'num_classes': 1000, 'input_size': (3, 299, 299), 'pool_size': (8, 8),
        'crop_pct': 0.875, 'interpolation': 'bicubic',
        'mean': (0.5, 0.5, 0.5), 'std': (0.5, 0.5, 0.5),
        'first_conv': 'features.0.conv', 'classifier': 'last_linear',
    },
})


@register_model
def inception_v4(pretrained=False, **kwargs):
    return _create_inception_v4('inception_v4', pretrained, **kwargs)
