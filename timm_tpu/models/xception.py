"""Xception (legacy Keras port) (reference: timm/models/xception.py:1-298),
TPU-native NHWC.

Depthwise-separable conv blocks with conv shortcuts; 299x299 eval. The
reference stores block bodies as Sequentials with interleaved paramless ReLU /
MaxPool entries — here blocks keep (sep, bn) pairs and the checkpoint filter
maps the reference's Sequential indices onto them.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import nnx

from ..layers import BatchNorm2d, Pool2d, SelectAdaptivePool2d, create_conv2d, trunc_normal_, zeros_
from ..layers.drop import Dropout
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._registry import generate_default_cfgs, register_model

__all__ = ['Xception']


class SeparableConv2d(nnx.Module):
    """dw conv (named ``conv1``) + pw conv (named ``pointwise``)
    (reference xception.py:25-54)."""

    def __init__(self, in_chs, out_chs, kernel_size=1, stride=1, padding=0, dilation=1,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.conv1 = create_conv2d(
            in_chs, in_chs, kernel_size, stride=stride, padding=padding, dilation=dilation,
            depthwise=True, **kw)
        self.pointwise = create_conv2d(in_chs, out_chs, 1, padding=0, **kw)

    def __call__(self, x):
        return self.pointwise(self.conv1(x))


class XceptionBlock(nnx.Module):
    """(reference xception.py:56-103)."""

    def __init__(self, in_chs, out_chs, reps, strides=1, start_with_relu=True, grow_first=True,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        if out_chs != in_chs or strides != 1:
            self.skip = create_conv2d(in_chs, out_chs, 1, stride=strides, padding=0, **kw)
            self.skipbn = BatchNorm2d(out_chs, rngs=rngs)
        else:
            self.skip = None
            self.skipbn = None
        self.start_with_relu = start_with_relu
        self.strides = strides
        pairs = []
        for i in range(reps):
            if grow_first:
                inc = in_chs if i == 0 else out_chs
                outc = out_chs
            else:
                inc = in_chs
                outc = in_chs if i < (reps - 1) else out_chs
            pairs.append(nnx.List([
                SeparableConv2d(inc, outc, 3, stride=1, padding=1, **kw),
                BatchNorm2d(outc, rngs=rngs),
            ]))
        self.rep = nnx.List(pairs)

    def __call__(self, x):
        inp = x
        for i, pair in enumerate(self.rep):
            if not (i == 0 and not self.start_with_relu):
                x = jax.nn.relu(x)
            x = pair[1](pair[0](x))
        if self.strides != 1:
            x = Pool2d('max', 3, self.strides, padding=1)(x)
        if self.skip is not None:
            skip = self.skipbn(self.skip(inp))
        else:
            skip = inp
        return x + skip


class Xception(nnx.Module):
    """(reference xception.py:105-250)."""

    def __init__(
            self,
            num_classes: int = 1000,
            in_chans: int = 3,
            drop_rate: float = 0.0,
            global_pool: str = 'avg',
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.drop_rate = drop_rate
        self.num_classes = num_classes
        self.num_features = self.head_hidden_size = 2048

        self.conv1 = create_conv2d(in_chans, 32, 3, stride=2, padding=0, **kw)
        self.bn1 = BatchNorm2d(32, rngs=rngs)
        self.conv2 = create_conv2d(32, 64, 3, padding=0, **kw)
        self.bn2 = BatchNorm2d(64, rngs=rngs)

        self.block1 = XceptionBlock(64, 128, 2, 2, start_with_relu=False, **kw)
        self.block2 = XceptionBlock(128, 256, 2, 2, **kw)
        self.block3 = XceptionBlock(256, 728, 2, 2, **kw)
        self.block4 = XceptionBlock(728, 728, 3, 1, **kw)
        self.block5 = XceptionBlock(728, 728, 3, 1, **kw)
        self.block6 = XceptionBlock(728, 728, 3, 1, **kw)
        self.block7 = XceptionBlock(728, 728, 3, 1, **kw)
        self.block8 = XceptionBlock(728, 728, 3, 1, **kw)
        self.block9 = XceptionBlock(728, 728, 3, 1, **kw)
        self.block10 = XceptionBlock(728, 728, 3, 1, **kw)
        self.block11 = XceptionBlock(728, 728, 3, 1, **kw)
        self.block12 = XceptionBlock(728, 1024, 2, 2, grow_first=False, **kw)

        self.conv3 = SeparableConv2d(1024, 1536, 3, 1, 1, **kw)
        self.bn3 = BatchNorm2d(1536, rngs=rngs)
        self.conv4 = SeparableConv2d(1536, self.num_features, 3, 1, 1, **kw)
        self.bn4 = BatchNorm2d(self.num_features, rngs=rngs)
        self.feature_info = [
            dict(num_chs=64, reduction=2, module='bn2'),
            dict(num_chs=128, reduction=4, module='block1'),
            dict(num_chs=256, reduction=8, module='block2'),
            dict(num_chs=728, reduction=16, module='block11'),
            dict(num_chs=2048, reduction=32, module='bn4'),
        ]
        self.global_pool = SelectAdaptivePool2d(pool_type=global_pool, flatten=True)
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        self.fc = nnx.Linear(
            self.num_features, num_classes, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs) if num_classes > 0 else None
        self._dtype = dtype
        self._param_dtype = param_dtype

    def no_weight_decay(self) -> set:
        return set()

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^conv[12]|bn[12]',
            blocks=[(r'^block(\d+)', None), (r'^conv[34]|bn[34]', (99,))],
        )

    def set_grad_checkpointing(self, enable: bool = True):
        assert not enable, 'gradient checkpointing not supported'

    def get_classifier(self):
        return self.fc

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = 'avg', *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            self.global_pool = SelectAdaptivePool2d(pool_type=global_pool, flatten=True)
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.fc = nnx.Linear(
            self.num_features, num_classes, kernel_init=trunc_normal_(std=0.02),
            dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs) if num_classes > 0 else None

    def forward_features(self, x):
        x = jax.nn.relu(self.bn1(self.conv1(x)))
        x = jax.nn.relu(self.bn2(self.conv2(x)))
        for i in range(1, 13):
            x = getattr(self, f'block{i}')(x)
        x = jax.nn.relu(self.bn3(self.conv3(x)))
        x = jax.nn.relu(self.bn4(self.conv4(x)))
        return x

    def forward_head(self, x, pre_logits: bool = False):
        x = self.global_pool(x)
        x = self.head_drop(x)
        if pre_logits or self.fc is None:
            return x
        return self.fc(x)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        """Feature points match feature_info: post-stem, block1, block2,
        block11 (pre-downsample input to block12), final act."""
        assert output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(5, indices)
        intermediates = []
        x = jax.nn.relu(self.bn1(self.conv1(x)))
        x = jax.nn.relu(self.bn2(self.conv2(x)))
        if 0 in take_indices:
            intermediates.append(x)
        feat_points = {1: 1, 2: 2, 3: 11}
        for i in range(1, 13):
            x = getattr(self, f'block{i}')(x)
            for fi, blk_i in feat_points.items():
                if blk_i == i and fi in take_indices:
                    intermediates.append(x)
            if stop_early and max_index < 4 and i >= feat_points.get(max_index, 12):
                if intermediates_only:
                    return intermediates
                return x, intermediates
        x = jax.nn.relu(self.bn3(self.conv3(x)))
        x = jax.nn.relu(self.bn4(self.conv4(x)))
        if 4 in take_indices:
            intermediates.append(x)
        if intermediates_only:
            return intermediates
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, _ = feature_take_indices(5, indices)
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict, model):
    """Map reference Sequential rep indices → (sep, bn) pair list. With a
    leading ReLU (all blocks but block1) sep_i is at 3i+1 and bn_i at 3i+2;
    without it sep_i is at 3i and bn_i at 3i+1."""
    import re

    from ._torch_convert import convert_torch_state_dict
    out = {}
    for k, v in state_dict.items():
        m = re.match(r'^(block\d+)\.rep\.(\d+)\.(.*)$', k)
        if m:
            blk, idx, rest = m.group(1), int(m.group(2)), m.group(3)
            swr = blk != 'block1'
            if swr:
                pair, kind = (idx - 1) // 3, (idx - 1) % 3
            else:
                pair, kind = idx // 3, idx % 3
            sub = 0 if kind == 0 else 1  # 0 → separable conv, 1 → bn
            k = f'{blk}.rep.{pair}.{sub}.{rest}'
        out[k] = v
    return convert_torch_state_dict(out, model)


def _cfg(url: str = '', **kwargs):
    return {
        'url': url, 'num_classes': 1000, 'input_size': (3, 299, 299), 'pool_size': (10, 10),
        'crop_pct': 0.8975, 'interpolation': 'bicubic',
        'mean': (0.5, 0.5, 0.5), 'std': (0.5, 0.5, 0.5),
        'first_conv': 'conv1', 'classifier': 'fc',
        'license': 'apache-2.0',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'legacy_xception.tf_in1k': _cfg(hf_hub_id='timm/'),
})


def _xception(variant, pretrained=False, **kwargs):
    return build_model_with_cfg(
        Xception, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(feature_cls='getter'),
        **kwargs,
    )


@register_model
def legacy_xception(pretrained=False, **kwargs) -> Xception:
    return _xception('legacy_xception', pretrained=pretrained, **kwargs)
