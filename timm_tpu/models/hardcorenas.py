"""HardCoRe-NAS (reference: timm/models/hardcorenas.py:1-157), TPU-native.

Six NAS-discovered MobileNetV3-style nets expressed as arch-string tables on
the shared EfficientNet builder; hard-sigmoid SE with ReLU inner act.
"""
from __future__ import annotations

from functools import partial

from ..layers import SqueezeExcite
from ._builder import build_model_with_cfg
from ._efficientnet_builder import decode_arch_def, resolve_act_layer, resolve_bn_args, round_channels
from ._registry import generate_default_cfgs, register_model
from .mobilenetv3 import MobileNetV3

__all__ = []


def checkpoint_filter_fn(state_dict, model):
    from .efficientnet import checkpoint_filter_fn as _eff_filter
    return _eff_filter(state_dict, model)


def _gen_hardcorenas(pretrained, variant, arch_def, **kwargs):
    """(reference hardcorenas.py:16-52)."""
    from ..layers import BatchNormAct2d
    se_layer = partial(
        SqueezeExcite, gate_layer='hard_sigmoid', force_act_layer='relu', rd_round_fn=round_channels)
    bn_args = resolve_bn_args(kwargs)
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def),
        num_features=1280,
        stem_size=32,
        act_layer=resolve_act_layer(kwargs, 'hard_swish'),
        se_layer=se_layer,
        **kwargs,
    )
    if bn_args:
        model_kwargs['norm_layer'] = partial(BatchNormAct2d, **bn_args)
    return build_model_with_cfg(
        MobileNetV3, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=tuple(range(len(arch_def)))),
        **model_kwargs,
    )


def _cfg(url='', **kwargs):
    return {
        'url': url, 'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': (7, 7),
        'crop_pct': 0.875, 'interpolation': 'bilinear',
        'mean': (0.485, 0.456, 0.406), 'std': (0.229, 0.224, 0.225),
        'first_conv': 'conv_stem', 'classifier': 'classifier',
        'license': 'apache-2.0',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'hardcorenas_a.miil_green_in1k': _cfg(hf_hub_id='timm/'),
    'hardcorenas_b.miil_green_in1k': _cfg(hf_hub_id='timm/'),
    'hardcorenas_c.miil_green_in1k': _cfg(hf_hub_id='timm/'),
    'hardcorenas_d.miil_green_in1k': _cfg(hf_hub_id='timm/'),
    'hardcorenas_e.miil_green_in1k': _cfg(hf_hub_id='timm/'),
    'hardcorenas_f.miil_green_in1k': _cfg(hf_hub_id='timm/'),
})


@register_model
def hardcorenas_a(pretrained=False, **kwargs) -> MobileNetV3:
    arch_def = [['ds_r1_k3_s1_e1_c16_nre'], ['ir_r1_k5_s2_e3_c24_nre', 'ir_r1_k5_s1_e3_c24_nre_se0.25'],
                ['ir_r1_k5_s2_e3_c40_nre', 'ir_r1_k5_s1_e6_c40_nre_se0.25'],
                ['ir_r1_k5_s2_e6_c80_se0.25', 'ir_r1_k5_s1_e6_c80_se0.25'],
                ['ir_r1_k5_s1_e6_c112_se0.25', 'ir_r1_k5_s1_e6_c112_se0.25'],
                ['ir_r1_k5_s2_e6_c192_se0.25', 'ir_r1_k5_s1_e6_c192_se0.25'], ['cn_r1_k1_s1_c960']]
    return _gen_hardcorenas(pretrained=pretrained, variant='hardcorenas_a', arch_def=arch_def, **kwargs)


@register_model
def hardcorenas_b(pretrained=False, **kwargs) -> MobileNetV3:
    arch_def = [['ds_r1_k3_s1_e1_c16_nre'],
                ['ir_r1_k5_s2_e3_c24_nre', 'ir_r1_k5_s1_e3_c24_nre_se0.25', 'ir_r1_k3_s1_e3_c24_nre'],
                ['ir_r1_k5_s2_e3_c40_nre', 'ir_r1_k5_s1_e3_c40_nre', 'ir_r1_k5_s1_e3_c40_nre'],
                ['ir_r1_k5_s2_e3_c80', 'ir_r1_k5_s1_e3_c80', 'ir_r1_k3_s1_e3_c80', 'ir_r1_k3_s1_e3_c80'],
                ['ir_r1_k5_s1_e3_c112', 'ir_r1_k3_s1_e3_c112', 'ir_r1_k3_s1_e3_c112', 'ir_r1_k3_s1_e3_c112'],
                ['ir_r1_k5_s2_e6_c192_se0.25', 'ir_r1_k5_s1_e6_c192_se0.25', 'ir_r1_k3_s1_e3_c192_se0.25'],
                ['cn_r1_k1_s1_c960']]
    return _gen_hardcorenas(pretrained=pretrained, variant='hardcorenas_b', arch_def=arch_def, **kwargs)


@register_model
def hardcorenas_c(pretrained=False, **kwargs) -> MobileNetV3:
    arch_def = [['ds_r1_k3_s1_e1_c16_nre'], ['ir_r1_k5_s2_e3_c24_nre', 'ir_r1_k5_s1_e3_c24_nre_se0.25'],
                ['ir_r1_k5_s2_e3_c40_nre', 'ir_r1_k5_s1_e3_c40_nre', 'ir_r1_k5_s1_e3_c40_nre',
                 'ir_r1_k5_s1_e3_c40_nre'],
                ['ir_r1_k5_s2_e4_c80', 'ir_r1_k5_s1_e6_c80_se0.25', 'ir_r1_k3_s1_e3_c80', 'ir_r1_k3_s1_e3_c80'],
                ['ir_r1_k5_s1_e6_c112_se0.25', 'ir_r1_k3_s1_e3_c112', 'ir_r1_k3_s1_e3_c112', 'ir_r1_k3_s1_e3_c112'],
                ['ir_r1_k5_s2_e6_c192_se0.25', 'ir_r1_k5_s1_e6_c192_se0.25', 'ir_r1_k3_s1_e3_c192_se0.25'],
                ['cn_r1_k1_s1_c960']]
    return _gen_hardcorenas(pretrained=pretrained, variant='hardcorenas_c', arch_def=arch_def, **kwargs)


@register_model
def hardcorenas_d(pretrained=False, **kwargs) -> MobileNetV3:
    arch_def = [['ds_r1_k3_s1_e1_c16_nre'], ['ir_r1_k5_s2_e3_c24_nre_se0.25', 'ir_r1_k5_s1_e3_c24_nre_se0.25'],
                ['ir_r1_k5_s2_e3_c40_nre_se0.25', 'ir_r1_k5_s1_e4_c40_nre_se0.25', 'ir_r1_k3_s1_e3_c40_nre_se0.25'],
                ['ir_r1_k5_s2_e4_c80_se0.25', 'ir_r1_k3_s1_e3_c80_se0.25', 'ir_r1_k3_s1_e3_c80_se0.25',
                 'ir_r1_k3_s1_e3_c80_se0.25'],
                ['ir_r1_k3_s1_e4_c112_se0.25', 'ir_r1_k5_s1_e4_c112_se0.25', 'ir_r1_k3_s1_e3_c112_se0.25',
                 'ir_r1_k5_s1_e3_c112_se0.25'],
                ['ir_r1_k5_s2_e6_c192_se0.25', 'ir_r1_k5_s1_e6_c192_se0.25', 'ir_r1_k5_s1_e6_c192_se0.25',
                 'ir_r1_k3_s1_e6_c192_se0.25'], ['cn_r1_k1_s1_c960']]
    return _gen_hardcorenas(pretrained=pretrained, variant='hardcorenas_d', arch_def=arch_def, **kwargs)


@register_model
def hardcorenas_e(pretrained=False, **kwargs) -> MobileNetV3:
    arch_def = [['ds_r1_k3_s1_e1_c16_nre'], ['ir_r1_k5_s2_e3_c24_nre_se0.25', 'ir_r1_k5_s1_e3_c24_nre_se0.25'],
                ['ir_r1_k5_s2_e6_c40_nre_se0.25', 'ir_r1_k5_s1_e4_c40_nre_se0.25', 'ir_r1_k5_s1_e4_c40_nre_se0.25',
                 'ir_r1_k3_s1_e3_c40_nre_se0.25'], ['ir_r1_k5_s2_e4_c80_se0.25', 'ir_r1_k3_s1_e6_c80_se0.25'],
                ['ir_r1_k5_s1_e6_c112_se0.25', 'ir_r1_k5_s1_e6_c112_se0.25', 'ir_r1_k5_s1_e6_c112_se0.25',
                 'ir_r1_k5_s1_e3_c112_se0.25'],
                ['ir_r1_k5_s2_e6_c192_se0.25', 'ir_r1_k5_s1_e6_c192_se0.25', 'ir_r1_k5_s1_e6_c192_se0.25',
                 'ir_r1_k3_s1_e6_c192_se0.25'], ['cn_r1_k1_s1_c960']]
    return _gen_hardcorenas(pretrained=pretrained, variant='hardcorenas_e', arch_def=arch_def, **kwargs)


@register_model
def hardcorenas_f(pretrained=False, **kwargs) -> MobileNetV3:
    arch_def = [['ds_r1_k3_s1_e1_c16_nre'], ['ir_r1_k5_s2_e3_c24_nre_se0.25', 'ir_r1_k5_s1_e3_c24_nre_se0.25'],
                ['ir_r1_k5_s2_e6_c40_nre_se0.25', 'ir_r1_k5_s1_e6_c40_nre_se0.25'],
                ['ir_r1_k5_s2_e6_c80_se0.25', 'ir_r1_k5_s1_e6_c80_se0.25', 'ir_r1_k3_s1_e3_c80_se0.25',
                 'ir_r1_k3_s1_e3_c80_se0.25'],
                ['ir_r1_k3_s1_e6_c112_se0.25', 'ir_r1_k5_s1_e6_c112_se0.25', 'ir_r1_k5_s1_e6_c112_se0.25',
                 'ir_r1_k3_s1_e3_c112_se0.25'],
                ['ir_r1_k5_s2_e6_c192_se0.25', 'ir_r1_k5_s1_e6_c192_se0.25', 'ir_r1_k3_s1_e6_c192_se0.25',
                 'ir_r1_k3_s1_e6_c192_se0.25'], ['cn_r1_k1_s1_c960']]
    return _gen_hardcorenas(pretrained=pretrained, variant='hardcorenas_f', arch_def=arch_def, **kwargs)
