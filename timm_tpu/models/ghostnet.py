"""GhostNet v1/v2, TPU-native NHWC
(reference: timm/models/ghostnet.py:1-1020; Han et al. 2020, Tang et al. 2022).

Ghost modules generate half the channels with a cheap depthwise conv over the
primary conv's output; v2 adds a decoupled-fully-connected attention branch
computed at half resolution and nearest-upsampled as a gate. GhostNetV3's
train-time re-parameterization variant is not implemented.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Union

import jax
import jax.numpy as jnp
from flax import nnx

from ..layers import (
    BatchNorm2d, Dropout, SelectAdaptivePool2d, SqueezeExcite, get_act_fn,
    make_divisible, trunc_normal_, zeros_,
)
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._registry import generate_default_cfgs, register_model

__all__ = ['GhostNet']

_SE_LAYER = partial(
    SqueezeExcite, gate_layer='hard_sigmoid', rd_round_fn=partial(make_divisible, divisor=4))


def _conv(in_chs, out_chs, k, stride=1, groups=1, *, rngs, **kw):
    pad = k // 2 if isinstance(k, int) else tuple(x // 2 for x in k)
    ks = (k, k) if isinstance(k, int) else k
    pads = [(pad, pad), (pad, pad)] if isinstance(pad, int) else [(pad[0], pad[0]), (pad[1], pad[1])]
    return nnx.Conv(in_chs, out_chs, kernel_size=ks, strides=stride, padding=pads,
                    feature_group_count=groups, use_bias=False, rngs=rngs, **kw)


def _avg_pool2(x):
    B, H, W, C = x.shape
    x = x[:, :2 * (H // 2), :2 * (W // 2)]
    return x.reshape(B, H // 2, 2, W // 2, 2, C).mean(axis=(2, 4))


class GhostModule(nnx.Module):
    """(reference ghostnet.py:36-71)."""

    def __init__(self, in_chs, out_chs, kernel_size=1, ratio=2, dw_size=3, stride=1,
                 act_layer='relu', *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.out_chs = out_chs
        init_chs = math.ceil(out_chs / ratio)
        new_chs = init_chs * (ratio - 1)
        kw = dict(dtype=dtype, param_dtype=param_dtype)
        self.primary_conv = _conv(in_chs, init_chs, kernel_size, stride, rngs=rngs, **kw)
        self.primary_bn = BatchNorm2d(init_chs, rngs=rngs)
        self.cheap_conv = _conv(init_chs, new_chs, dw_size, 1, groups=init_chs, rngs=rngs, **kw)
        self.cheap_bn = BatchNorm2d(new_chs, rngs=rngs)
        self.act = get_act_fn(act_layer) if act_layer is not None else None

    def _primary(self, x):
        x = self.primary_bn(self.primary_conv(x))
        return self.act(x) if self.act is not None else x

    def _cheap(self, x):
        x = self.cheap_bn(self.cheap_conv(x))
        return self.act(x) if self.act is not None else x

    def __call__(self, x):
        x1 = self._primary(x)
        x2 = self._cheap(x1)
        return jnp.concatenate([x1, x2], axis=-1)[..., :self.out_chs]


class GhostModuleV2(GhostModule):
    """Ghost module + DFC attention gate (reference ghostnet.py:74-119)."""

    def __init__(self, in_chs, out_chs, kernel_size=1, ratio=2, dw_size=3, stride=1,
                 act_layer='relu', *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        super().__init__(in_chs, out_chs, kernel_size, ratio, dw_size, stride,
                         act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        kw = dict(dtype=dtype, param_dtype=param_dtype)
        self.short_conv1 = _conv(in_chs, out_chs, kernel_size, stride, rngs=rngs, **kw)
        self.short_bn1 = BatchNorm2d(out_chs, rngs=rngs)
        self.short_conv2 = _conv(out_chs, out_chs, (1, 5), 1, groups=out_chs, rngs=rngs, **kw)
        self.short_bn2 = BatchNorm2d(out_chs, rngs=rngs)
        self.short_conv3 = _conv(out_chs, out_chs, (5, 1), 1, groups=out_chs, rngs=rngs, **kw)
        self.short_bn3 = BatchNorm2d(out_chs, rngs=rngs)

    def __call__(self, x):
        res = _avg_pool2(x)
        res = self.short_bn1(self.short_conv1(res))
        res = self.short_bn2(self.short_conv2(res))
        res = self.short_bn3(self.short_conv3(res))
        x1 = self._primary(x)
        x2 = self._cheap(x1)
        out = jnp.concatenate([x1, x2], axis=-1)[..., :self.out_chs]
        gate = jax.nn.sigmoid(res)
        gate = jax.image.resize(gate, (gate.shape[0], out.shape[1], out.shape[2], gate.shape[3]),
                                method='nearest')
        return out * gate


class GhostBottleneck(nnx.Module):
    """(reference ghostnet.py:357-446)."""

    def __init__(self, in_chs, mid_chs, out_chs, dw_kernel_size=3, stride=1,
                 act_layer='relu', se_ratio=0.0, mode='original',
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        has_se = se_ratio is not None and se_ratio > 0.0
        self.stride = stride
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        ghost_cls = GhostModule if mode == 'original' else GhostModuleV2
        self.ghost1 = ghost_cls(in_chs, mid_chs, act_layer=act_layer, **kw)
        if stride > 1:
            self.conv_dw = _conv(mid_chs, mid_chs, dw_kernel_size, stride, groups=mid_chs,
                                 rngs=rngs, dtype=dtype, param_dtype=param_dtype)
            self.bn_dw = BatchNorm2d(mid_chs, rngs=rngs)
        else:
            self.conv_dw = None
            self.bn_dw = None
        self.se = _SE_LAYER(mid_chs, rd_ratio=se_ratio, **kw) if has_se else None
        self.ghost2 = GhostModule(mid_chs, out_chs, act_layer=None, **kw)
        if in_chs == out_chs and stride == 1:
            self.shortcut_dw = None
        else:
            self.shortcut_dw = _conv(in_chs, in_chs, dw_kernel_size, stride, groups=in_chs,
                                     rngs=rngs, dtype=dtype, param_dtype=param_dtype)
            self.shortcut_bn1 = BatchNorm2d(in_chs, rngs=rngs)
            self.shortcut_pw = _conv(in_chs, out_chs, 1, 1, rngs=rngs,
                                     dtype=dtype, param_dtype=param_dtype)
            self.shortcut_bn2 = BatchNorm2d(out_chs, rngs=rngs)

    def __call__(self, x):
        shortcut = x
        x = self.ghost1(x)
        if self.conv_dw is not None:
            x = self.bn_dw(self.conv_dw(x))
        if self.se is not None:
            x = self.se(x)
        x = self.ghost2(x)
        if self.shortcut_dw is None:
            return x + shortcut
        s = self.shortcut_bn1(self.shortcut_dw(shortcut))
        s = self.shortcut_bn2(self.shortcut_pw(s))
        return x + s


class _ConvBnAct(nnx.Module):
    def __init__(self, in_chs, out_chs, k, *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.conv = _conv(in_chs, out_chs, k, 1, rngs=rngs, dtype=dtype, param_dtype=param_dtype)
        self.bn1 = BatchNorm2d(out_chs, rngs=rngs)

    def __call__(self, x):
        return nnx.relu(self.bn1(self.conv(x)))


class GhostNet(nnx.Module):
    """GhostNet with the reference's model contract (reference ghostnet.py:641-945)."""

    def __init__(
            self,
            cfgs,
            num_classes: int = 1000,
            width: float = 1.0,
            in_chans: int = 3,
            output_stride: int = 32,
            global_pool: str = 'avg',
            drop_rate: float = 0.2,
            version: str = 'v1',
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert output_stride == 32
        self.num_classes = num_classes
        self.drop_rate = drop_rate
        self.grad_checkpointing = False
        self.feature_info = []
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        stem_chs = make_divisible(16 * width, 4)
        self.conv_stem = _conv(in_chans, stem_chs, 3, 2, rngs=rngs, dtype=dtype, param_dtype=param_dtype)
        self.feature_info.append(dict(num_chs=stem_chs, reduction=2, module='conv_stem'))
        self.bn1 = BatchNorm2d(stem_chs, rngs=rngs)
        prev_chs = stem_chs

        stages = []
        stage_idx = 0
        layer_idx = 0
        net_stride = 2
        exp_size = 16
        self.stage_ends = []  # block-stage index for each post-stem feature entry
        for cfg in cfgs:
            layers = []
            s = 1
            for k, exp_size, c, se_ratio, s in cfg:
                out_chs = make_divisible(c * width, 4)
                mid_chs = make_divisible(exp_size * width, 4)
                mode = 'attn' if (version == 'v2' and layer_idx > 1) else 'original'
                layers.append(GhostBottleneck(
                    prev_chs, mid_chs, out_chs, k, s, se_ratio=se_ratio, mode=mode, **kw))
                prev_chs = out_chs
                layer_idx += 1
            if s > 1:
                net_stride *= 2
                self.feature_info.append(dict(
                    num_chs=prev_chs, reduction=net_stride, module=f'blocks.{stage_idx}'))
                self.stage_ends.append(stage_idx)
            stages.append(nnx.List(layers))
            stage_idx += 1
        out_chs = make_divisible(exp_size * width, 4)
        stages.append(nnx.List([_ConvBnAct(prev_chs, out_chs, 1, **kw)]))
        self.blocks = nnx.List(stages)
        prev_chs = out_chs

        self.num_features = prev_chs
        self.head_hidden_size = 1280
        self.global_pool = SelectAdaptivePool2d(pool_type=global_pool, flatten=False)
        self.conv_head = nnx.Conv(
            prev_chs, 1280, kernel_size=(1, 1), use_bias=True,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        self.classifier = nnx.Linear(
            1280, num_classes, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs) if num_classes > 0 else None
        self._dtype = dtype
        self._param_dtype = param_dtype

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return set()

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^conv_stem|bn1',
            blocks=[
                (r'^blocks\.(\d+)' if coarse else r'^blocks\.(\d+)\.(\d+)', None),
                (r'conv_head', (99999,)),
            ],
        )

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def get_classifier(self):
        return self.classifier

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            self.global_pool = SelectAdaptivePool2d(pool_type=global_pool, flatten=False)
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.classifier = nnx.Linear(
            self.head_hidden_size, num_classes, kernel_init=trunc_normal_(std=0.02),
            dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs) if num_classes > 0 else None

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        from ._manipulate import checkpoint_seq
        x = nnx.relu(self.bn1(self.conv_stem(x)))
        for stage in self.blocks:
            if self.grad_checkpointing:
                x = checkpoint_seq(stage, x)
            else:
                for b in stage:
                    x = b(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        x = self.global_pool(x)
        if x.ndim == 2:
            x = x[:, None, None, :]
        x = nnx.relu(self.conv_head(x))
        x = x.reshape(x.shape[0], -1)
        x = self.head_drop(x)
        if pre_logits or self.classifier is None:
            return x
        return self.classifier(x)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        # indices address FEATURE entries (stem + one per stride change),
        # mapped onto block-stage indices via self.stage_ends
        assert output_fmt == 'NHWC'
        num_features = 1 + len(self.stage_ends)
        take_indices, max_index = feature_take_indices(num_features, indices)
        take_stages = {self.stage_ends[i - 1]: i for i in take_indices if i > 0}
        max_stage = self.stage_ends[max_index - 1] if max_index > 0 else -1
        x = nnx.relu(self.bn1(self.conv_stem(x)))
        intermediates = []
        if 0 in take_indices:
            intermediates.append(x)
        for i, stage in enumerate(self.blocks):
            if stop_early and i > max_stage:
                break
            for b in stage:
                x = b(x)
            if i in take_stages:
                intermediates.append(x)
        if intermediates_only:
            return intermediates
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        num_features = 1 + len(self.stage_ends)
        take_indices, max_index = feature_take_indices(num_features, indices)
        max_stage = self.stage_ends[max_index - 1] if max_index > 0 else 0
        self.blocks = nnx.List(list(self.blocks)[:max_stage + 1])
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict, model):
    from ._torch_convert import convert_torch_state_dict
    import re
    out = {}
    remap = [
        (r'\.primary_conv\.0\.', '.primary_conv.'),
        (r'\.primary_conv\.1\.', '.primary_bn.'),
        (r'\.cheap_operation\.0\.', '.cheap_conv.'),
        (r'\.cheap_operation\.1\.', '.cheap_bn.'),
        (r'\.short_conv\.0\.', '.short_conv1.'),
        (r'\.short_conv\.1\.', '.short_bn1.'),
        (r'\.short_conv\.2\.', '.short_conv2.'),
        (r'\.short_conv\.3\.', '.short_bn2.'),
        (r'\.short_conv\.4\.', '.short_conv3.'),
        (r'\.short_conv\.5\.', '.short_bn3.'),
        (r'\.shortcut\.0\.', '.shortcut_dw.'),
        (r'\.shortcut\.1\.', '.shortcut_bn1.'),
        (r'\.shortcut\.2\.', '.shortcut_pw.'),
        (r'\.shortcut\.3\.', '.shortcut_bn2.'),
        (r'\.se\.conv_reduce\.', '.se.fc1.'),
        (r'\.se\.conv_expand\.', '.se.fc2.'),
    ]
    for k, v in state_dict.items():
        for pat, rep in remap:
            k = re.sub(pat, rep, k)
        out[k] = v
    return convert_torch_state_dict(out, model)


def _create_ghostnet(variant, width=1.0, pretrained=False, **kwargs):
    cfgs = [
        # k, t, c, SE, s
        [[3, 16, 16, 0, 1]],
        [[3, 48, 24, 0, 2]],
        [[3, 72, 24, 0, 1]],
        [[5, 72, 40, 0.25, 2]],
        [[5, 120, 40, 0.25, 1]],
        [[3, 240, 80, 0, 2]],
        [[3, 200, 80, 0, 1],
         [3, 184, 80, 0, 1],
         [3, 184, 80, 0, 1],
         [3, 480, 112, 0.25, 1],
         [3, 672, 112, 0.25, 1]],
        [[5, 672, 160, 0.25, 2]],
        [[5, 960, 160, 0, 1],
         [5, 960, 160, 0.25, 1],
         [5, 960, 160, 0, 1],
         [5, 960, 160, 0.25, 1]],
    ]
    return build_model_with_cfg(
        GhostNet, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(flatten_sequential=True),
        cfgs=cfgs, width=width,
        **kwargs,
    )


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': (7, 7),
        'crop_pct': 0.875, 'interpolation': 'bicubic',
        'mean': (0.485, 0.456, 0.406), 'std': (0.229, 0.224, 0.225),
        'first_conv': 'conv_stem', 'classifier': 'classifier',
        'license': 'apache-2.0',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'ghostnet_050.untrained': _cfg(),
    'ghostnet_100.in1k': _cfg(hf_hub_id='timm/'),
    'ghostnet_130.untrained': _cfg(),
    'ghostnetv2_100.in1k': _cfg(hf_hub_id='timm/'),
    'ghostnetv2_130.in1k': _cfg(hf_hub_id='timm/'),
    'ghostnetv2_160.in1k': _cfg(hf_hub_id='timm/'),
})


@register_model
def ghostnet_050(pretrained=False, **kwargs) -> GhostNet:
    return _create_ghostnet('ghostnet_050', width=0.5, pretrained=pretrained, **kwargs)


@register_model
def ghostnet_100(pretrained=False, **kwargs) -> GhostNet:
    return _create_ghostnet('ghostnet_100', width=1.0, pretrained=pretrained, **kwargs)


@register_model
def ghostnet_130(pretrained=False, **kwargs) -> GhostNet:
    return _create_ghostnet('ghostnet_130', width=1.3, pretrained=pretrained, **kwargs)


@register_model
def ghostnetv2_100(pretrained=False, **kwargs) -> GhostNet:
    return _create_ghostnet('ghostnetv2_100', width=1.0, pretrained=pretrained, version='v2', **kwargs)


@register_model
def ghostnetv2_130(pretrained=False, **kwargs) -> GhostNet:
    return _create_ghostnet('ghostnetv2_130', width=1.3, pretrained=pretrained, version='v2', **kwargs)


@register_model
def ghostnetv2_160(pretrained=False, **kwargs) -> GhostNet:
    return _create_ghostnet('ghostnetv2_160', width=1.6, pretrained=pretrained, version='v2', **kwargs)
