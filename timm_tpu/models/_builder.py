"""Model build + pretrained-load orchestration
(reference: timm/models/_builder.py:43-503).
"""
from __future__ import annotations

import dataclasses
import logging
import os
from copy import deepcopy
from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp
import numpy as np
from flax import nnx

from ._helpers import clean_state_dict, load_state_dict, load_state_dict_into_model
from ._pretrained import PretrainedCfg
from ._registry import get_pretrained_cfg, split_model_name_tag

_logger = logging.getLogger(__name__)

__all__ = ['build_model_with_cfg', 'resolve_pretrained_cfg', 'load_pretrained', 'adapt_input_conv']


def adapt_input_conv(in_chans: int, conv_weight: np.ndarray) -> np.ndarray:
    """Adapt a first-conv HWIO kernel to a different input channel count
    (reference _builder.py:245-259, _manipulate.py:289)."""
    conv_weight = np.asarray(conv_weight, dtype=np.float32)
    KH, KW, I, O = conv_weight.shape
    if in_chans == I:
        return conv_weight
    if in_chans == 1:
        return conv_weight.sum(axis=2, keepdims=True)
    if I != 3:
        raise NotImplementedError('Weight format not supported by conversion.')
    repeat = -(-in_chans // I)
    w = np.tile(conv_weight, (1, 1, repeat, 1))[:, :, :in_chans]
    w *= (3 / float(in_chans))
    return w


def _resolve_pretrained_source(pretrained_cfg: PretrainedCfg):
    cfg_source = pretrained_cfg.source or ''
    if pretrained_cfg.state_dict is not None:
        return 'state_dict', pretrained_cfg.state_dict
    if pretrained_cfg.file:
        return 'file', pretrained_cfg.file
    if pretrained_cfg.url:
        return 'url', pretrained_cfg.url
    if pretrained_cfg.hf_hub_id:
        return 'hf-hub', pretrained_cfg.hf_hub_id
    return '', None


def resolve_pretrained_cfg(
        variant: str,
        pretrained_cfg=None,
        pretrained_cfg_overlay=None,
) -> PretrainedCfg:
    model_with_tag = variant
    pretrained_tag = None
    if pretrained_cfg:
        if isinstance(pretrained_cfg, dict):
            pretrained_cfg = PretrainedCfg(**pretrained_cfg)
        elif isinstance(pretrained_cfg, str):
            pretrained_tag = pretrained_cfg
            pretrained_cfg = None
    if not pretrained_cfg:
        if pretrained_tag:
            model_with_tag = '.'.join([variant, pretrained_tag])
        pretrained_cfg = get_pretrained_cfg(model_with_tag)
    if not pretrained_cfg:
        _logger.info(
            f'No pretrained configuration specified for {model_with_tag}. '
            f'Using a default; accuracy/input-size metadata may be incorrect.')
        pretrained_cfg = PretrainedCfg()
    pretrained_cfg_overlay = pretrained_cfg_overlay or {}
    if not pretrained_cfg.architecture:
        pretrained_cfg_overlay.setdefault('architecture', variant)
    pretrained_cfg = dataclasses.replace(pretrained_cfg, **pretrained_cfg_overlay)
    return pretrained_cfg


def load_pretrained(
        model: nnx.Module,
        pretrained_cfg: Optional[PretrainedCfg] = None,
        num_classes: int = 1000,
        in_chans: int = 3,
        filter_fn: Optional[Callable] = None,
        strict: bool = True,
):
    """Load pretrained weights, adapting stem/classifier (reference _builder.py:152-281)."""
    pretrained_cfg = pretrained_cfg or getattr(model, 'pretrained_cfg', None)
    if not pretrained_cfg:
        raise RuntimeError('Invalid pretrained config, cannot load weights.')
    load_from, pretrained_loc = _resolve_pretrained_source(pretrained_cfg)
    if load_from == 'state_dict':
        state_dict = dict(pretrained_loc)
    elif load_from == 'file':
        state_dict = load_state_dict(pretrained_loc)
    elif load_from in ('url', 'hf-hub'):
        raise RuntimeError(
            f'Pretrained weights for this model resolve to a remote source ({load_from}: {pretrained_loc}). '
            'This environment has no network egress — download the file and pass '
            "pretrained_cfg_overlay=dict(file='/path/to/weights.safetensors').")
    else:
        raise RuntimeError('No pretrained weights exist for this model. Use `pretrained=False`.')

    if filter_fn is not None:
        try:
            state_dict = filter_fn(state_dict, model)
        except TypeError:
            state_dict = filter_fn(state_dict)

    input_convs = pretrained_cfg.first_conv
    if input_convs is not None and in_chans != 3:
        if isinstance(input_convs, str):
            input_convs = (input_convs,)
        for input_conv_name in input_convs:
            weight_name = input_conv_name + '.kernel'
            if weight_name in state_dict:
                try:
                    state_dict[weight_name] = adapt_input_conv(in_chans, state_dict[weight_name])
                    _logger.info(f'Converted input conv {input_conv_name} to {in_chans} chans')
                except NotImplementedError:
                    del state_dict[weight_name]
                    strict = False
                    _logger.warning(f'Unable to convert input conv {input_conv_name}; random init used.')

    classifiers = pretrained_cfg.classifier
    label_offset = pretrained_cfg.label_offset or 0
    if classifiers is not None:
        if isinstance(classifiers, str):
            classifiers = (classifiers,)
        if num_classes != pretrained_cfg.num_classes:
            for classifier_name in classifiers:
                state_dict.pop(classifier_name + '.kernel', None)
                state_dict.pop(classifier_name + '.bias', None)
            strict = False
        elif label_offset > 0:
            for classifier_name in classifiers:
                kname = classifier_name + '.kernel'
                bname = classifier_name + '.bias'
                if kname in state_dict:
                    state_dict[kname] = state_dict[kname][..., label_offset:]
                if bname in state_dict:
                    state_dict[bname] = state_dict[bname][label_offset:]

    load_state_dict_into_model(model, state_dict, strict=strict)


def _filter_kwargs(kwargs: Dict, names):
    if not kwargs or not names:
        return
    for n in names:
        kwargs.pop(n, None)


def _update_default_model_kwargs(pretrained_cfg: PretrainedCfg, kwargs: Dict, kwargs_filter):
    """Push cfg defaults into model kwargs (reference _builder.py:307-345)."""
    default_kwarg_names = ('num_classes', 'global_pool', 'in_chans')
    if pretrained_cfg.fixed_input_size:
        default_kwarg_names += ('img_size',)
    for n in default_kwarg_names:
        if n == 'img_size':
            input_size = pretrained_cfg.input_size
            if input_size is not None:
                assert len(input_size) == 3
                kwargs.setdefault(n, input_size[-2:])
        elif n == 'in_chans':
            input_size = pretrained_cfg.input_size
            if input_size is not None:
                assert len(input_size) == 3
                kwargs.setdefault(n, input_size[0])
        elif n == 'num_classes':
            v = pretrained_cfg.num_classes
            if v is not None:
                kwargs.setdefault(n, v)
        else:
            v = getattr(pretrained_cfg, n, None)
            if v is not None:
                kwargs.setdefault(n, v)
    _filter_kwargs(kwargs, names=kwargs_filter)


def build_model_with_cfg(
        model_cls: Callable,
        variant: str,
        pretrained: bool,
        pretrained_cfg: Optional[Dict] = None,
        pretrained_cfg_overlay: Optional[Dict] = None,
        model_cfg: Optional[Any] = None,
        feature_cfg: Optional[Dict] = None,
        pretrained_strict: bool = True,
        pretrained_filter_fn: Optional[Callable] = None,
        kwargs_filter=None,
        **kwargs,
):
    """Instantiate a model from an entrypoint + cfg (reference _builder.py:384-503)."""
    if kwargs.pop('pruned', False):
        raise NotImplementedError('pruned model variants are not supported yet')
    features = False
    feature_cfg = feature_cfg or {}

    pretrained_cfg = resolve_pretrained_cfg(
        variant, pretrained_cfg=pretrained_cfg, pretrained_cfg_overlay=pretrained_cfg_overlay)
    pretrained_cfg_dict = pretrained_cfg.to_dict()
    _update_default_model_kwargs(pretrained_cfg, kwargs, kwargs_filter)

    if kwargs.pop('features_only', False):
        features = True
        feature_cfg.setdefault('out_indices', (0, 1, 2, 3, 4))
        if 'out_indices' in kwargs:
            feature_cfg['out_indices'] = kwargs.pop('out_indices')
        if 'feature_cls' in kwargs:
            feature_cfg['feature_cls'] = kwargs.pop('feature_cls')

    rngs = kwargs.pop('rngs', None)
    if rngs is None:
        seed = kwargs.pop('seed', 0)
        rngs = nnx.Rngs(params=seed, dropout=seed + 1)
    else:
        kwargs.pop('seed', None)

    if model_cfg is None:
        model = model_cls(rngs=rngs, **kwargs)
    else:
        model = model_cls(cfg=model_cfg, rngs=rngs, **kwargs)
    model.pretrained_cfg = pretrained_cfg
    model.default_cfg = pretrained_cfg_dict  # backwards-compat alias

    if pretrained:
        load_pretrained(
            model,
            pretrained_cfg=pretrained_cfg,
            num_classes=kwargs.get('num_classes', 1000),
            in_chans=kwargs.get('in_chans', 3),
            filter_fn=pretrained_filter_fn,
            strict=pretrained_strict,
        )

    if features:
        from ._features import FeatureGetterNet
        model = FeatureGetterNet(model, **feature_cfg)
    return model
