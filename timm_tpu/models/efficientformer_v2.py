"""EfficientFormer-V2 — rethinking ViTs for MobileNet size/speed (NHWC / nnx).

Re-implements reference timm/models/efficientformer_v2.py:1-946
(EfficientFormerV2 s0/s1/s2/l): conv stem, conv-MLP blocks with a mid dw conv,
2D attention with talking heads + local-v dw branch (strided w/ bilinear
upsample in stage 3), and attention-augmented downsampling into stage 4.

TPU notes: all spatial ops run NHWC; attention q/k/v come from 1x1 convs so
the token reshape is layout-free. The attention bias tables are per-resolution
static gathers (reuse of levit's index helper, stride-2 for the downsample
attention), and the stride-attention upsample is a static-shape bilinear
resize. Talking-head mixing runs as a 1x1 NHWC conv over the head axis.
"""
import math
from functools import partial
from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from flax import nnx

from timm_tpu.data.constants import IMAGENET_DEFAULT_MEAN, IMAGENET_DEFAULT_STD
from ..layers import (
    BatchNorm2d, Dropout, DropPath, LayerScale,
    calculate_drop_path_rates, get_act_fn, to_2tuple, to_ntuple, trunc_normal_, zeros_,
)
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._registry import generate_default_cfgs, register_model
from .levit import _attention_bias_idxs

__all__ = ['EfficientFormerV2']

EfficientFormer_width = {
    'L': (40, 80, 192, 384),
    'S2': (32, 64, 144, 288),
    'S1': (32, 48, 120, 224),
    'S0': (32, 48, 96, 176),
}

EfficientFormer_depth = {
    'L': (5, 5, 15, 10),
    'S2': (4, 4, 12, 8),
    'S1': (3, 3, 9, 6),
    'S0': (2, 2, 6, 4),
}

EfficientFormer_expansion_ratios = {
    'L': (4, 4, (4, 4, 4, 4, 3, 3, 3, 3, 3, 3, 3, 4, 4, 4, 4), (4, 4, 4, 3, 3, 3, 3, 4, 4, 4)),
    'S2': (4, 4, (4, 4, 3, 3, 3, 3, 3, 3, 4, 4, 4, 4), (4, 4, 3, 3, 3, 3, 4, 4)),
    'S1': (4, 4, (4, 4, 3, 3, 3, 3, 4, 4, 4), (4, 4, 3, 3, 4, 4)),
    'S0': (4, 4, (4, 3, 3, 3, 4, 4), (4, 3, 3, 4)),
}


class ConvNorm(nnx.Module):
    """Conv (bias, torch-symmetric padding) + BN (reference efficientformer_v2.py:69-104)."""

    def __init__(self, in_chs, out_chs, kernel_size=1, stride=1, padding=None, dilation=1,
                 groups=1, bias=True, norm_layer=BatchNorm2d,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kernel_size = to_2tuple(kernel_size)
        if padding is None:
            padding = tuple(((k - 1) * dilation) // 2 for k in kernel_size)
        padding = to_2tuple(padding)
        self.conv = nnx.Conv(
            in_chs, out_chs, kernel_size=kernel_size, strides=stride,
            padding=[(padding[0], padding[0]), (padding[1], padding[1])],
            kernel_dilation=(dilation, dilation), feature_group_count=groups, use_bias=bias,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn = norm_layer(out_chs, rngs=rngs)

    def __call__(self, x):
        return self.bn(self.conv(x))


class ConvNormAct(nnx.Module):
    """ConvNorm + act; children named conv/bn to match checkpoints."""

    def __init__(self, in_chs, out_chs, kernel_size=1, stride=1, groups=1, bias=True,
                 norm_layer=BatchNorm2d, act_layer='gelu',
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kernel_size = to_2tuple(kernel_size)
        padding = tuple((k - 1) // 2 for k in kernel_size)
        self.conv = nnx.Conv(
            in_chs, out_chs, kernel_size=kernel_size, strides=stride,
            padding=[(padding[0], padding[0]), (padding[1], padding[1])],
            feature_group_count=groups, use_bias=bias,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn = norm_layer(out_chs, rngs=rngs)
        self.act = get_act_fn(act_layer)

    def __call__(self, x):
        return self.act(self.bn(self.conv(x)))


class Attention2d(nnx.Module):
    """2D attention with talking heads, local-v dw branch, and optional
    stride-2 operation with bilinear upsample (reference :107-230)."""

    def __init__(self, dim=384, key_dim=32, num_heads=8, attn_ratio=4, resolution=7,
                 act_layer='gelu', stride=None, norm_layer=BatchNorm2d,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(norm_layer=norm_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.num_heads = num_heads
        self.scale = key_dim ** -0.5
        self.key_dim = key_dim

        resolution = to_2tuple(resolution)
        if stride is not None:
            resolution = tuple(math.ceil(r / stride) for r in resolution)
            self.stride_conv = ConvNorm(dim, dim, kernel_size=3, stride=stride, groups=dim, **kw)
            self.upsample_stride = stride
        else:
            self.stride_conv = None
            self.upsample_stride = None
        self.resolution = resolution
        self.N = resolution[0] * resolution[1]
        self.d = int(attn_ratio * key_dim)
        self.dh = self.d * num_heads
        kh = key_dim * num_heads

        self.q = ConvNorm(dim, kh, **kw)
        self.k = ConvNorm(dim, kh, **kw)
        self.v = ConvNorm(dim, self.dh, **kw)
        self.v_local = ConvNorm(self.dh, self.dh, kernel_size=3, groups=self.dh, **kw)
        # talking heads: 1x1 convs over the head axis (attn laid out (B,N,M,heads))
        th = partial(nnx.Conv, kernel_size=(1, 1), use_bias=True,
                     dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.talking_head1 = th(num_heads, num_heads)
        self.talking_head2 = th(num_heads, num_heads)
        self.act = get_act_fn(act_layer)
        self.proj = ConvNorm(self.dh, dim, 1, **kw)

        self.attention_biases = nnx.Param(jnp.zeros((num_heads, self.N), param_dtype))
        # nnx.Variable: raw array attrs break nnx graph traversal on older flax
        self._bias_idxs = nnx.Variable(jnp.asarray(_attention_bias_idxs(resolution)))

    def __call__(self, x):
        B, H0, W0, C = x.shape
        if self.stride_conv is not None:
            x = self.stride_conv(x)
        B, H, W, _ = x.shape
        N = H * W

        q = self.q(x).reshape(B, N, self.num_heads, self.key_dim)
        k = self.k(x).reshape(B, N, self.num_heads, self.key_dim)
        v_map = self.v(x)
        v_local = self.v_local(v_map)
        v = v_map.reshape(B, N, self.num_heads, self.d)

        attn = jnp.einsum('bnhd,bmhd->bnmh', q, k) * self.scale
        bias = self.attention_biases[...][:, self._bias_idxs[...]].transpose(1, 2, 0)  # (N, N, H)
        attn = attn + bias.astype(attn.dtype)
        attn = self.talking_head1(attn)
        attn = jax.nn.softmax(attn, axis=2)
        attn = self.talking_head2(attn)

        x = jnp.einsum('bnmh,bmhd->bnhd', attn, v).reshape(B, H, W, self.dh)
        x = x + v_local
        if self.upsample_stride is not None:
            x = jax.image.resize(x, (B, H0, W0, self.dh), method='bilinear')
        x = self.act(x)
        return self.proj(x)


class LocalGlobalQuery(nnx.Module):
    """Stride-2 query: dw conv + 1x1-kernel stride-2 'pool' (a plain
    subsample), summed then projected (reference :233-252)."""

    def __init__(self, in_dim, out_dim, *, dtype=None, param_dtype=jnp.float32,
                 norm_layer=BatchNorm2d, rngs: nnx.Rngs):
        self.local = nnx.Conv(
            in_dim, in_dim, kernel_size=(3, 3), strides=2, padding=[(1, 1), (1, 1)],
            feature_group_count=in_dim, use_bias=True,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.proj = ConvNorm(in_dim, out_dim, 1, norm_layer=norm_layer,
                             dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        pool_q = x[:, ::2, ::2, :]  # AvgPool2d(1, 2, 0) == stride-2 subsample
        local_q = self.local(x)
        return self.proj(local_q + pool_q)


class Attention2dDownsample(nnx.Module):
    """Attention with stride-2 queries producing a downsampled map
    (reference efficientformer_v2.py:255-368)."""

    def __init__(self, dim=384, key_dim=16, num_heads=8, attn_ratio=4, resolution=7,
                 out_dim=None, act_layer='gelu', norm_layer=BatchNorm2d,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(norm_layer=norm_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.num_heads = num_heads
        self.scale = key_dim ** -0.5
        self.key_dim = key_dim
        self.resolution = to_2tuple(resolution)
        self.resolution2 = tuple(math.ceil(r / 2) for r in self.resolution)
        self.N = self.resolution[0] * self.resolution[1]
        self.N2 = self.resolution2[0] * self.resolution2[1]
        self.d = int(attn_ratio * key_dim)
        self.dh = self.d * num_heads
        self.out_dim = out_dim or dim
        kh = key_dim * num_heads

        self.q = LocalGlobalQuery(dim, kh, norm_layer=norm_layer,
                                  dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.k = ConvNorm(dim, kh, 1, **kw)
        self.v = ConvNorm(dim, self.dh, 1, **kw)
        self.v_local = ConvNorm(self.dh, self.dh, kernel_size=3, stride=2, groups=self.dh, **kw)
        self.act = get_act_fn(act_layer)
        self.proj = ConvNorm(self.dh, self.out_dim, 1, **kw)

        self.attention_biases = nnx.Param(jnp.zeros((num_heads, self.N), param_dtype))
        self._bias_idxs = nnx.Variable(jnp.asarray(_attention_bias_idxs(self.resolution, stride=2)))  # (N2, N)

    def __call__(self, x):
        B, H, W, C = x.shape
        q = self.q(x).reshape(B, self.N2, self.num_heads, self.key_dim)
        k = self.k(x).reshape(B, self.N, self.num_heads, self.key_dim)
        v_map = self.v(x)
        v_local = self.v_local(v_map)
        v = v_map.reshape(B, self.N, self.num_heads, self.d)

        attn = jnp.einsum('bnhd,bmhd->bhnm', q, k) * self.scale
        bias = self.attention_biases[...][:, self._bias_idxs[...]]  # (H, N2, N)
        attn = jax.nn.softmax(attn + bias.astype(attn.dtype), axis=-1)

        x = jnp.einsum('bhnm,bmhd->bnhd', attn, v).reshape(
            B, self.resolution2[0], self.resolution2[1], self.dh)
        x = self.act(x + v_local)
        return self.proj(x)


class Downsample(nnx.Module):
    """Strided ConvNorm, optionally summed with attention downsampling
    (reference efficientformer_v2.py:371-418)."""

    def __init__(self, in_chs, out_chs, kernel_size=3, stride=2, padding=1, resolution=7,
                 use_attn=False, act_layer='gelu', norm_layer=BatchNorm2d,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.conv = ConvNorm(
            in_chs, out_chs, kernel_size=kernel_size, stride=stride, padding=padding,
            norm_layer=norm_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.attn = Attention2dDownsample(
            dim=in_chs, out_dim=out_chs, resolution=resolution, act_layer=act_layer,
            norm_layer=norm_layer, dtype=dtype, param_dtype=param_dtype,
            rngs=rngs) if use_attn else None

    def __call__(self, x):
        out = self.conv(x)
        if self.attn is not None:
            return self.attn(x) + out
        return out


class ConvMlpWithNorm(nnx.Module):
    """1x1 conv MLP with optional mid dw conv (reference :421-475)."""

    def __init__(self, in_features, hidden_features=None, out_features=None,
                 act_layer='gelu', norm_layer=BatchNorm2d, drop=0.0, mid_conv=False,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        out_features = out_features or in_features
        hidden_features = hidden_features or in_features
        kw = dict(norm_layer=norm_layer, act_layer=act_layer,
                  dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.fc1 = ConvNormAct(in_features, hidden_features, 1, bias=True, **kw)
        self.mid = ConvNormAct(hidden_features, hidden_features, 3,
                               groups=hidden_features, bias=True, **kw) if mid_conv else None
        self.drop1 = Dropout(drop, rngs=rngs)
        self.fc2 = ConvNorm(hidden_features, out_features, 1, norm_layer=norm_layer,
                            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.drop2 = Dropout(drop, rngs=rngs)

    def __call__(self, x):
        x = self.fc1(x)
        if self.mid is not None:
            x = self.mid(x)
        x = self.drop1(x)
        return self.drop2(self.fc2(x))


class EfficientFormerV2Block(nnx.Module):
    """Optional attention mixer + conv MLP, each with LayerScale
    (reference efficientformer_v2.py:478-530)."""

    def __init__(self, dim, mlp_ratio=4., act_layer='gelu', norm_layer=BatchNorm2d,
                 proj_drop=0., drop_path=0., layer_scale_init_value=1e-5,
                 resolution=7, stride=None, use_attn=True,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        if use_attn:
            self.token_mixer = Attention2d(
                dim, resolution=resolution, act_layer=act_layer, stride=stride,
                norm_layer=norm_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            self.ls1 = LayerScale(dim, layer_scale_init_value, param_dtype=param_dtype,
                                  rngs=rngs) if layer_scale_init_value is not None else None
            self.drop_path1 = DropPath(drop_path, rngs=rngs) if drop_path > 0. else None
        else:
            self.token_mixer = None
            self.ls1 = None
            self.drop_path1 = None
        self.mlp = ConvMlpWithNorm(
            dim, int(dim * mlp_ratio), act_layer=act_layer, norm_layer=norm_layer,
            drop=proj_drop, mid_conv=True, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.ls2 = LayerScale(dim, layer_scale_init_value, param_dtype=param_dtype,
                              rngs=rngs) if layer_scale_init_value is not None else None
        self.drop_path2 = DropPath(drop_path, rngs=rngs) if drop_path > 0. else None

    def __call__(self, x):
        if self.token_mixer is not None:
            y = self.token_mixer(x)
            y = self.ls1(y) if self.ls1 is not None else y
            x = x + (self.drop_path1(y) if self.drop_path1 is not None else y)
        y = self.mlp(x)
        y = self.ls2(y) if self.ls2 is not None else y
        return x + (self.drop_path2(y) if self.drop_path2 is not None else y)


class Stem4(nnx.Module):
    """Two strided ConvNormActs, stride 4 (reference :533-566)."""

    def __init__(self, in_chs, out_chs, act_layer='gelu', norm_layer=BatchNorm2d,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(norm_layer=norm_layer, act_layer=act_layer,
                  dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.stride = 4
        self.conv1 = ConvNormAct(in_chs, out_chs // 2, kernel_size=3, stride=2, bias=True, **kw)
        self.conv2 = ConvNormAct(out_chs // 2, out_chs, kernel_size=3, stride=2, bias=True, **kw)

    def __call__(self, x):
        return self.conv2(self.conv1(x))


class EfficientFormerV2Stage(nnx.Module):
    """Downsample + blocks; the last num_vit blocks attend
    (reference efficientformer_v2.py:569-638)."""

    def __init__(self, dim, dim_out, depth, resolution=7, downsample=True,
                 block_stride=None, downsample_use_attn=False, block_use_attn=False,
                 num_vit=1, mlp_ratio=4., proj_drop=0., drop_path=0.,
                 layer_scale_init_value=1e-5, act_layer='gelu', norm_layer=BatchNorm2d,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(act_layer=act_layer, norm_layer=norm_layer,
                  dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.grad_checkpointing = False
        mlp_ratio = to_ntuple(depth)(mlp_ratio)
        resolution = to_2tuple(resolution)

        if downsample:
            self.downsample = Downsample(
                dim, dim_out, use_attn=downsample_use_attn, resolution=resolution, **kw)
            dim = dim_out
            resolution = tuple(math.ceil(r / 2) for r in resolution)
        else:
            assert dim == dim_out
            self.downsample = None

        blocks = []
        for block_idx in range(depth):
            remain_idx = depth - num_vit - 1
            blocks.append(EfficientFormerV2Block(
                dim, resolution=resolution, stride=block_stride,
                mlp_ratio=mlp_ratio[block_idx],
                use_attn=block_use_attn and block_idx > remain_idx,
                proj_drop=proj_drop,
                drop_path=drop_path[block_idx] if isinstance(drop_path, (list, tuple)) else drop_path,
                layer_scale_init_value=layer_scale_init_value,
                **kw))
        self.blocks = nnx.List(blocks)

    def __call__(self, x):
        if self.downsample is not None:
            x = self.downsample(x)
        remat_blk = nnx.remat(EfficientFormerV2Block.__call__) if self.grad_checkpointing else None
        for blk in self.blocks:
            x = remat_blk(blk, x) if remat_blk is not None else blk(x)
        return x


class EfficientFormerV2(nnx.Module):
    """EfficientFormerV2 (reference efficientformer_v2.py:641-860)."""

    def __init__(
            self,
            depths: Tuple[int, ...],
            in_chans: int = 3,
            img_size: Union[int, Tuple[int, int]] = 224,
            global_pool: str = 'avg',
            embed_dims: Optional[Tuple[int, ...]] = None,
            downsamples: Optional[Tuple[bool, ...]] = None,
            mlp_ratios=4,
            norm_layer=BatchNorm2d,
            norm_eps: float = 1e-5,
            act_layer='gelu',
            num_classes: int = 1000,
            drop_rate: float = 0.,
            proj_drop_rate: float = 0.,
            drop_path_rate: float = 0.,
            layer_scale_init_value: Optional[float] = 1e-5,
            num_vit: int = 0,
            distillation: bool = True,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: Optional[nnx.Rngs] = None,
    ):
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        assert global_pool in ('avg', '')
        norm_layer = partial(norm_layer, eps=norm_eps)
        kw = dict(act_layer=act_layer, norm_layer=norm_layer,
                  dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.num_classes = num_classes
        self.global_pool = global_pool
        self.feature_info = []
        img_size = to_2tuple(img_size)
        self._dd = dict(dtype=dtype, param_dtype=param_dtype)

        self.stem = Stem4(in_chans, embed_dims[0], **kw)
        prev_dim = embed_dims[0]
        stride = 4

        num_stages = len(depths)
        dpr = calculate_drop_path_rates(drop_path_rate, depths, stagewise=True)
        downsamples = downsamples or (False,) + (True,) * (num_stages - 1)
        mlp_ratios = to_ntuple(num_stages)(mlp_ratios)
        stages = []
        for i in range(num_stages):
            curr_resolution = tuple(math.ceil(s / stride) for s in img_size)
            stages.append(EfficientFormerV2Stage(
                prev_dim, embed_dims[i], depth=depths[i], resolution=curr_resolution,
                downsample=downsamples[i],
                block_stride=2 if i == 2 else None,
                downsample_use_attn=i >= 3,
                block_use_attn=i >= 2,
                num_vit=num_vit,
                mlp_ratio=mlp_ratios[i],
                proj_drop=proj_drop_rate,
                drop_path=dpr[i],
                layer_scale_init_value=layer_scale_init_value,
                **kw))
            if downsamples[i]:
                stride *= 2
            prev_dim = embed_dims[i]
            self.feature_info += [dict(num_chs=prev_dim, reduction=stride, module=f'stages.{i}')]
        self.stages = nnx.List(stages)

        self.num_features = self.head_hidden_size = embed_dims[-1]
        self.norm = norm_layer(embed_dims[-1], rngs=rngs)
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        linear = partial(nnx.Linear, use_bias=True, kernel_init=trunc_normal_(std=0.02),
                         bias_init=zeros_, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.head = linear(embed_dims[-1], num_classes) if num_classes > 0 else None
        self.dist = distillation
        self.head_dist = linear(embed_dims[-1], num_classes) if (distillation and num_classes > 0) else None
        self.distilled_training = False

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return {'attention_biases'}

    def group_matcher(self, coarse: bool = False):
        return dict(stem=r'^stem', blocks=[(r'^stages\.(\d+)', None), (r'^norm', (99999,))])

    def set_grad_checkpointing(self, enable: bool = True):
        for s in self.stages:
            s.grad_checkpointing = enable

    def set_distilled_training(self, enable: bool = True):
        self.distilled_training = enable

    def get_classifier(self):
        return self.head, self.head_dist

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            self.global_pool = global_pool
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        if num_classes > 0:
            linear = partial(nnx.Linear, use_bias=True, kernel_init=trunc_normal_(std=0.02),
                             bias_init=zeros_, rngs=rngs, **self._dd)
            self.head = linear(self.num_features, num_classes)
            self.head_dist = linear(self.num_features, num_classes) if self.dist else None
        else:
            self.head = None
            self.head_dist = None

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        x = self.stem(x)
        for stage in self.stages:
            x = stage(x)
        return self.norm(x) if self.norm is not None else x

    def forward_head(self, x, pre_logits: bool = False):
        if self.global_pool == 'avg':
            x = x.mean(axis=(1, 2))
        x = self.head_drop(x)
        if pre_logits or self.head is None:
            return x
        if self.head_dist is None:
            return self.head(x)
        x, x_dist = self.head(x), self.head_dist(x)
        if self.distilled_training and not self.head_drop.deterministic:
            return x, x_dist
        return (x + x_dist) / 2

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(self, x, indices=None, norm: bool = False,
                              stop_early: bool = False, output_fmt: str = 'NHWC',
                              intermediates_only: bool = False):
        assert output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        intermediates = []
        x = self.stem(x)
        last_idx = len(self.stages) - 1
        stages = self.stages if not stop_early else self.stages[:max_index + 1]
        feat_idx = 0
        for feat_idx, stage in enumerate(stages):
            x = stage(x)
            if feat_idx in take_indices:
                if feat_idx == last_idx and norm and self.norm is not None:
                    intermediates.append(self.norm(x))
                else:
                    intermediates.append(x)
        if intermediates_only:
            return intermediates
        if feat_idx == last_idx and self.norm is not None:
            x = self.norm(x)
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        self.stages = nnx.List(list(self.stages)[:max_index + 1])
        if prune_norm:
            self.norm = None
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict, model):
    from ._torch_convert import convert_torch_state_dict
    if 'model' in state_dict:
        state_dict = state_dict['model']
    state_dict = {k: v for k, v in state_dict.items() if 'attention_bias_idxs' not in k}
    return convert_torch_state_dict(state_dict, model)


def _cfg(url: str = '', **kwargs):
    return {
        'url': url,
        'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': None, 'fixed_input_size': True,
        'crop_pct': .95, 'interpolation': 'bicubic',
        'mean': IMAGENET_DEFAULT_MEAN, 'std': IMAGENET_DEFAULT_STD,
        'classifier': ('head', 'head_dist'), 'first_conv': 'stem.conv1.conv',
        'license': 'apache-2.0',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'efficientformerv2_s0.snap_dist_in1k': _cfg(),
    'efficientformerv2_s1.snap_dist_in1k': _cfg(),
    'efficientformerv2_s2.snap_dist_in1k': _cfg(),
    'efficientformerv2_l.snap_dist_in1k': _cfg(),
})


def _create_efficientformerv2(variant, pretrained=False, **kwargs):
    out_indices = kwargs.pop('out_indices', (0, 1, 2, 3))
    return build_model_with_cfg(
        EfficientFormerV2, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=out_indices, feature_cls='getter'),
        **kwargs,
    )


@register_model
def efficientformerv2_s0(pretrained=False, **kwargs) -> EfficientFormerV2:
    model_args = dict(
        depths=EfficientFormer_depth['S0'], embed_dims=EfficientFormer_width['S0'],
        num_vit=2, drop_path_rate=0.0, mlp_ratios=EfficientFormer_expansion_ratios['S0'])
    return _create_efficientformerv2('efficientformerv2_s0', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def efficientformerv2_s1(pretrained=False, **kwargs) -> EfficientFormerV2:
    model_args = dict(
        depths=EfficientFormer_depth['S1'], embed_dims=EfficientFormer_width['S1'],
        num_vit=2, drop_path_rate=0.0, mlp_ratios=EfficientFormer_expansion_ratios['S1'])
    return _create_efficientformerv2('efficientformerv2_s1', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def efficientformerv2_s2(pretrained=False, **kwargs) -> EfficientFormerV2:
    model_args = dict(
        depths=EfficientFormer_depth['S2'], embed_dims=EfficientFormer_width['S2'],
        num_vit=4, drop_path_rate=0.02, mlp_ratios=EfficientFormer_expansion_ratios['S2'])
    return _create_efficientformerv2('efficientformerv2_s2', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def efficientformerv2_l(pretrained=False, **kwargs) -> EfficientFormerV2:
    model_args = dict(
        depths=EfficientFormer_depth['L'], embed_dims=EfficientFormer_width['L'],
        num_vit=6, drop_path_rate=0.1, mlp_ratios=EfficientFormer_expansion_ratios['L'])
    return _create_efficientformerv2('efficientformerv2_l', pretrained=pretrained, **dict(model_args, **kwargs))
