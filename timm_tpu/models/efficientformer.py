"""EfficientFormer — ViTs at MobileNet speed (NHWC / nnx).

Re-implements reference timm/models/efficientformer.py:1-686
(EfficientFormer l1/l3/l7): conv stem, three pool-mixer (MetaFormer-style)
stages, and a final stage that flattens to tokens for LeViT-style biased
attention blocks, with a distilled dual classifier head.

TPU notes: spatial blocks run NHWC end-to-end; the Flat transition is one
reshape (channels are already last, no permute needed, unlike the NCHW
reference). The attention bias is a static dr*W+dc gather folded by XLA into
the logits add.
"""
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import nnx

from timm_tpu.data.constants import IMAGENET_DEFAULT_MEAN, IMAGENET_DEFAULT_STD
from ..layers import (
    BatchNorm2d, Dropout, DropPath, LayerNorm, LayerScale, Mlp,
    calculate_drop_path_rates, get_act_fn, to_2tuple, trunc_normal_, zeros_,
)
from ..layers.pool import Pool2d
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._registry import generate_default_cfgs, register_model
from .levit import _attention_bias_idxs

__all__ = ['EfficientFormer']

EfficientFormer_width = {
    'l1': (48, 96, 224, 448),
    'l3': (64, 128, 320, 512),
    'l7': (96, 192, 384, 768),
}

EfficientFormer_depth = {
    'l1': (3, 2, 6, 4),
    'l3': (4, 4, 12, 6),
    'l7': (6, 6, 18, 8),
}


class EfficientFormerAttention(nnx.Module):
    """LeViT-style attention whose bias table is indexed by the offset
    ``|dr|*W + |dc|`` (reference efficientformer.py:53-119); the index table
    is the stride-1 case of levit's helper."""

    def __init__(self, dim=384, key_dim=32, num_heads=8, attn_ratio=4, resolution=7,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.num_heads = num_heads
        self.scale = key_dim ** -0.5
        self.key_dim = key_dim
        self.key_attn_dim = key_dim * num_heads
        self.val_dim = int(attn_ratio * key_dim)
        self.val_attn_dim = self.val_dim * num_heads

        linear = partial(nnx.Linear, use_bias=True, kernel_init=trunc_normal_(std=0.02),
                         bias_init=zeros_, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.qkv = linear(dim, self.key_attn_dim * 2 + self.val_attn_dim)
        self.proj = linear(self.val_attn_dim, dim)

        resolution = to_2tuple(resolution)
        self.attention_biases = nnx.Param(
            jnp.zeros((num_heads, resolution[0] * resolution[1]), param_dtype))
        # nnx.Variable: raw array attrs break nnx graph traversal on older flax
        self._bias_idxs = nnx.Variable(jnp.asarray(_attention_bias_idxs(resolution)))

    def __call__(self, x):
        B, N, C = x.shape
        qkv = self.qkv(x).reshape(B, N, self.num_heads, -1).transpose(0, 2, 1, 3)
        q, k, v = jnp.split(qkv, [self.key_dim, 2 * self.key_dim], axis=3)
        bias = self.attention_biases[...][:, self._bias_idxs[...]].astype(q.dtype)  # (H, N, N)
        attn = (q @ k.transpose(0, 1, 3, 2)) * self.scale + bias
        attn = jax.nn.softmax(attn, axis=-1)
        x = (attn @ v).transpose(0, 2, 1, 3).reshape(B, N, self.val_attn_dim)
        return self.proj(x)


class Stem4(nnx.Module):
    """Two strided conv+norm+act, stride 4 (reference efficientformer.py:122-145)."""

    def __init__(self, in_chs, out_chs, act_layer='relu', norm_layer=BatchNorm2d,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        conv = partial(nnx.Conv, kernel_size=(3, 3), strides=2, padding=[(1, 1), (1, 1)],
                       use_bias=True, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.stride = 4
        self.conv1 = conv(in_chs, out_chs // 2)
        self.norm1 = norm_layer(out_chs // 2, rngs=rngs)
        self.conv2 = conv(out_chs // 2, out_chs)
        self.norm2 = norm_layer(out_chs, rngs=rngs)
        self.act = get_act_fn(act_layer)

    def __call__(self, x):
        x = self.act(self.norm1(self.conv1(x)))
        return self.act(self.norm2(self.conv2(x)))


class Downsample(nnx.Module):
    """Strided conv + norm (reference efficientformer.py:148-177)."""

    def __init__(self, in_chs, out_chs, kernel_size=3, stride=2, padding=None,
                 norm_layer=BatchNorm2d, *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        if padding is None:
            padding = kernel_size // 2
        self.conv = nnx.Conv(
            in_chs, out_chs, kernel_size=to_2tuple(kernel_size), strides=stride,
            padding=[(padding, padding), (padding, padding)], use_bias=True,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.norm = norm_layer(out_chs, rngs=rngs)

    def __call__(self, x):
        return self.norm(self.conv(x))


class Flat(nnx.Module):
    """(B, H, W, C) → (B, N, C); occupies a block index so checkpoint block
    numbering matches the reference Sequential (efficientformer.py:180-186)."""

    def __call__(self, x):
        B, H, W, C = x.shape
        return x.reshape(B, H * W, C)


class Pooling(nnx.Module):
    """avgpool(x) - x mixer, count_include_pad=False (reference :189-200)."""

    def __init__(self, pool_size=3):
        self.pool = Pool2d('avg', pool_size, 1, pool_size // 2)

    def __call__(self, x):
        return self.pool(x) - x


class ConvMlpWithNorm(nnx.Module):
    """1x1 conv MLP with norms (reference efficientformer.py:203-239)."""

    def __init__(self, in_features, hidden_features=None, out_features=None,
                 act_layer='gelu', norm_layer=BatchNorm2d, drop=0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        out_features = out_features or in_features
        hidden_features = hidden_features or in_features
        conv = partial(nnx.Conv, kernel_size=(1, 1), use_bias=True,
                       dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.fc1 = conv(in_features, hidden_features)
        self.norm1 = norm_layer(hidden_features, rngs=rngs)
        self.act = get_act_fn(act_layer)
        self.fc2 = conv(hidden_features, out_features)
        self.norm2 = norm_layer(out_features, rngs=rngs)
        self.drop = Dropout(drop, rngs=rngs)

    def __call__(self, x):
        x = self.drop(self.act(self.norm1(self.fc1(x))))
        return self.drop(self.norm2(self.fc2(x)))


class MetaBlock1d(nnx.Module):
    """Token block: LN → biased attention → LS, LN → MLP → LS
    (reference efficientformer.py:242-271)."""

    def __init__(self, dim, mlp_ratio=4., act_layer='gelu', norm_layer=None,
                 proj_drop=0., drop_path=0., layer_scale_init_value=1e-5,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        norm_layer = norm_layer or partial(LayerNorm, eps=1e-5)
        self.norm1 = norm_layer(dim, rngs=rngs)
        self.token_mixer = EfficientFormerAttention(dim, **kw)
        self.ls1 = LayerScale(dim, layer_scale_init_value, param_dtype=param_dtype, rngs=rngs)
        self.drop_path1 = DropPath(drop_path, rngs=rngs) if drop_path > 0. else None
        self.norm2 = norm_layer(dim, rngs=rngs)
        self.mlp = Mlp(dim, int(dim * mlp_ratio), act_layer=act_layer, drop=proj_drop, **kw)
        self.ls2 = LayerScale(dim, layer_scale_init_value, param_dtype=param_dtype, rngs=rngs)
        self.drop_path2 = DropPath(drop_path, rngs=rngs) if drop_path > 0. else None

    def __call__(self, x):
        y = self.ls1(self.token_mixer(self.norm1(x)))
        x = x + (self.drop_path1(y) if self.drop_path1 is not None else y)
        y = self.ls2(self.mlp(self.norm2(x)))
        return x + (self.drop_path2(y) if self.drop_path2 is not None else y)


class MetaBlock2d(nnx.Module):
    """Spatial block: pool mixer → LS, conv MLP → LS (reference :274-308)."""

    def __init__(self, dim, pool_size=3, mlp_ratio=4., act_layer='gelu',
                 norm_layer=BatchNorm2d, proj_drop=0., drop_path=0.,
                 layer_scale_init_value=1e-5,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.token_mixer = Pooling(pool_size=pool_size)
        self.ls1 = LayerScale(dim, layer_scale_init_value, param_dtype=param_dtype, rngs=rngs)
        self.drop_path1 = DropPath(drop_path, rngs=rngs) if drop_path > 0. else None
        self.mlp = ConvMlpWithNorm(dim, int(dim * mlp_ratio), act_layer=act_layer,
                                   norm_layer=norm_layer, drop=proj_drop, **kw)
        self.ls2 = LayerScale(dim, layer_scale_init_value, param_dtype=param_dtype, rngs=rngs)
        self.drop_path2 = DropPath(drop_path, rngs=rngs) if drop_path > 0. else None

    def __call__(self, x):
        y = self.ls1(self.token_mixer(x))
        x = x + (self.drop_path1(y) if self.drop_path1 is not None else y)
        y = self.ls2(self.mlp(x))
        return x + (self.drop_path2(y) if self.drop_path2 is not None else y)


class EfficientFormerStage(nnx.Module):
    """Downsample + 2d blocks, with the last num_vit blocks running as token
    blocks after a Flat transition (reference efficientformer.py:311-378)."""

    def __init__(self, dim, dim_out, depth, downsample=True, num_vit=1, pool_size=3,
                 mlp_ratio=4., act_layer='gelu', norm_layer=BatchNorm2d, norm_layer_cl=None,
                 proj_drop=0., drop_path=0., layer_scale_init_value=1e-5,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.grad_checkpointing = False
        if downsample:
            self.downsample = Downsample(dim, dim_out, norm_layer=norm_layer, **kw)
            dim = dim_out
        else:
            assert dim == dim_out
            self.downsample = None

        blocks = []
        if num_vit and num_vit >= depth:
            blocks.append(Flat())
        for block_idx in range(depth):
            remain_idx = depth - block_idx - 1
            dp = drop_path[block_idx] if isinstance(drop_path, (list, tuple)) else drop_path
            if num_vit and num_vit > remain_idx:
                blocks.append(MetaBlock1d(
                    dim, mlp_ratio=mlp_ratio, act_layer=act_layer, norm_layer=norm_layer_cl,
                    proj_drop=proj_drop, drop_path=dp,
                    layer_scale_init_value=layer_scale_init_value, **kw))
            else:
                blocks.append(MetaBlock2d(
                    dim, pool_size=pool_size, mlp_ratio=mlp_ratio, act_layer=act_layer,
                    norm_layer=norm_layer, proj_drop=proj_drop, drop_path=dp,
                    layer_scale_init_value=layer_scale_init_value, **kw))
                if num_vit and num_vit == remain_idx:
                    blocks.append(Flat())
        self.blocks = nnx.List(blocks)

    def __call__(self, x):
        if self.downsample is not None:
            x = self.downsample(x)
        remat1 = nnx.remat(MetaBlock1d.__call__) if self.grad_checkpointing else None
        remat2 = nnx.remat(MetaBlock2d.__call__) if self.grad_checkpointing else None
        for blk in self.blocks:
            if self.grad_checkpointing and isinstance(blk, MetaBlock1d):
                x = remat1(blk, x)
            elif self.grad_checkpointing and isinstance(blk, MetaBlock2d):
                x = remat2(blk, x)
            else:
                x = blk(x)
        return x


class EfficientFormer(nnx.Module):
    """EfficientFormer (reference efficientformer.py:381-592)."""

    def __init__(
            self,
            depths: Tuple[int, ...] = (3, 2, 6, 4),
            embed_dims: Tuple[int, ...] = (48, 96, 224, 448),
            in_chans: int = 3,
            num_classes: int = 1000,
            global_pool: str = 'avg',
            downsamples: Optional[Tuple[bool, ...]] = None,
            num_vit: int = 0,
            mlp_ratios: float = 4,
            pool_size: int = 3,
            layer_scale_init_value: float = 1e-5,
            act_layer='gelu',
            norm_layer=BatchNorm2d,
            norm_layer_cl=None,
            drop_rate: float = 0.,
            proj_drop_rate: float = 0.,
            drop_path_rate: float = 0.,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: Optional[nnx.Rngs] = None,
    ):
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        norm_layer_cl = norm_layer_cl or partial(LayerNorm, eps=1e-5)
        self.num_classes = num_classes
        self.global_pool = global_pool
        self._dd = dict(dtype=dtype, param_dtype=param_dtype)

        self.stem = Stem4(in_chans, embed_dims[0], norm_layer=norm_layer, **kw)
        prev_dim = embed_dims[0]

        self.num_stages = len(depths)
        last_stage = self.num_stages - 1
        dpr = calculate_drop_path_rates(drop_path_rate, depths, stagewise=True)
        downsamples = downsamples or (False,) + (True,) * (self.num_stages - 1)
        stages = []
        self.feature_info = []
        for i in range(self.num_stages):
            stages.append(EfficientFormerStage(
                prev_dim, embed_dims[i], depths[i],
                downsample=downsamples[i],
                num_vit=num_vit if i == last_stage else 0,
                pool_size=pool_size, mlp_ratio=mlp_ratios, act_layer=act_layer,
                norm_layer_cl=norm_layer_cl, norm_layer=norm_layer,
                proj_drop=proj_drop_rate, drop_path=dpr[i],
                layer_scale_init_value=layer_scale_init_value, **kw))
            prev_dim = embed_dims[i]
            self.feature_info += [dict(num_chs=embed_dims[i], reduction=2 ** (i + 2), module=f'stages.{i}')]
        self.stages = nnx.List(stages)

        self.num_features = self.head_hidden_size = embed_dims[-1]
        self.norm = norm_layer_cl(self.num_features, rngs=rngs)
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        linear = partial(nnx.Linear, use_bias=True, kernel_init=trunc_normal_(std=0.02),
                         bias_init=zeros_, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        # the released checkpoints are all distilled → dual heads, averaged at eval
        self.head = linear(self.num_features, num_classes) if num_classes > 0 else None
        self.head_dist = linear(self.num_features, num_classes) if num_classes > 0 else None
        self.distilled_training = False

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return {'attention_biases'}

    def group_matcher(self, coarse: bool = False):
        return dict(stem=r'^stem', blocks=[(r'^stages\.(\d+)', None), (r'^norm', (99999,))])

    def set_grad_checkpointing(self, enable: bool = True):
        for s in self.stages:
            s.grad_checkpointing = enable

    def set_distilled_training(self, enable: bool = True):
        self.distilled_training = enable

    def get_classifier(self):
        return self.head, self.head_dist

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            self.global_pool = global_pool
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        if num_classes > 0:
            linear = partial(nnx.Linear, use_bias=True, kernel_init=trunc_normal_(std=0.02),
                             bias_init=zeros_, rngs=rngs, **self._dd)
            self.head = linear(self.num_features, num_classes)
            self.head_dist = linear(self.num_features, num_classes)
        else:
            self.head = None
            self.head_dist = None

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        x = self.stem(x)
        for stage in self.stages:
            x = stage(x)
        return self.norm(x) if self.norm is not None else x

    def forward_head(self, x, pre_logits: bool = False):
        if self.global_pool == 'avg':
            x = x.mean(axis=1)
        x = self.head_drop(x)
        if pre_logits or self.head is None:
            return x
        x, x_dist = self.head(x), self.head_dist(x)
        if self.distilled_training and not self.head_drop.deterministic:
            return x, x_dist
        return (x + x_dist) / 2

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(self, x, indices=None, norm: bool = False,
                              stop_early: bool = False, output_fmt: str = 'NHWC',
                              intermediates_only: bool = False):
        assert output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        intermediates = []
        x = self.stem(x)
        last_idx = self.num_stages - 1
        B, H, W, C = x.shape
        stages = self.stages if not stop_early else self.stages[:max_index + 1]
        feat_idx = 0
        for feat_idx, stage in enumerate(stages):
            x = stage(x)
            if feat_idx < last_idx:
                B, H, W, C = x.shape
            if feat_idx in take_indices:
                if feat_idx == last_idx:
                    # tokens → NHWC map at the final (post-Flat) stage
                    x_inter = self.norm(x) if norm and self.norm is not None else x
                    intermediates.append(x_inter.reshape(B, H // 2, W // 2, -1))
                else:
                    intermediates.append(x)
        if intermediates_only:
            return intermediates
        if feat_idx == last_idx and self.norm is not None:
            x = self.norm(x)
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        self.stages = nnx.List(list(self.stages)[:max_index + 1])
        if prune_norm:
            self.norm = None
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict, model):
    from ._torch_convert import convert_torch_state_dict
    if 'model' in state_dict:
        state_dict = state_dict['model']
    state_dict = {k: v for k, v in state_dict.items() if 'attention_bias_idxs' not in k}
    return convert_torch_state_dict(state_dict, model)


def _cfg(url: str = '', **kwargs):
    return {
        'url': url,
        'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': None, 'fixed_input_size': True,
        'crop_pct': .95, 'interpolation': 'bicubic',
        'mean': IMAGENET_DEFAULT_MEAN, 'std': IMAGENET_DEFAULT_STD,
        'first_conv': 'stem.conv1', 'classifier': ('head', 'head_dist'),
        'license': 'apache-2.0',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'efficientformer_l1.snap_dist_in1k': _cfg(),
    'efficientformer_l3.snap_dist_in1k': _cfg(),
    'efficientformer_l7.snap_dist_in1k': _cfg(),
})


def _create_efficientformer(variant, pretrained=False, **kwargs):
    out_indices = kwargs.pop('out_indices', 4)
    return build_model_with_cfg(
        EfficientFormer, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=out_indices, feature_cls='getter'),
        kwargs_filter=('img_size',),  # fixed_input_size cfg, but the model is size-agnostic
        **kwargs,
    )


@register_model
def efficientformer_l1(pretrained=False, **kwargs) -> EfficientFormer:
    model_args = dict(depths=EfficientFormer_depth['l1'], embed_dims=EfficientFormer_width['l1'], num_vit=1)
    return _create_efficientformer('efficientformer_l1', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def efficientformer_l3(pretrained=False, **kwargs) -> EfficientFormer:
    model_args = dict(depths=EfficientFormer_depth['l3'], embed_dims=EfficientFormer_width['l3'], num_vit=4)
    return _create_efficientformer('efficientformer_l3', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def efficientformer_l7(pretrained=False, **kwargs) -> EfficientFormer:
    model_args = dict(depths=EfficientFormer_depth['l7'], embed_dims=EfficientFormer_width['l7'], num_vit=8)
    return _create_efficientformer('efficientformer_l7', pretrained=pretrained, **dict(model_args, **kwargs))
