"""Normalization-Free Networks: NFNet-F, NF-RegNet, NF-ResNet
(reference: timm/models/nfnet.py:1-1189; Brock et al. 2021,
arXiv:2101.08692 + arXiv:2102.06171).

TPU-first notes:
  * No BatchNorm anywhere — signal propagation is controlled by ScaledStdConv
    weight standardization + analytic alpha/beta variance bookkeeping, which
    makes every block a pure function of its inputs: ideal for `jit`, no
    cross-replica stat sync, no train/eval divergence in the trunk.
  * AGC (adaptive gradient clipping), the training-side half of the NFNet
    recipe, already lives in `timm_tpu/utils/clip_grad.py` and plugs into the
    jitted train step via `--clip-mode agc`.
  * The activation-correcting gamma constants fold into the conv weight
    standardization scale (`gamma_in_act=False` default) exactly as the
    reference does; dm_ variants keep gamma in the activation and use TF-SAME
    padding for DeepMind weight compatibility.
"""
from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax.numpy as jnp
from flax import nnx

from ..layers import (
    ClassifierHead, DropPath, ScaledStdConv2d, calculate_drop_path_rates,
    get_act_fn, get_attn, make_divisible,
)
from ..layers.std_conv import ScaledStdConv2dSame
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._manipulate import checkpoint_seq
from ._registry import generate_default_cfgs, register_model
from .resnet import avg_pool2d, max_pool2d

__all__ = ['NormFreeNet', 'NfCfg']


@dataclass
class NfCfg:
    """Normalization-free network config (reference nfnet.py:39-61)."""
    depths: Tuple[int, int, int, int]
    channels: Tuple[int, int, int, int]
    alpha: float = 0.2
    stem_type: str = '3x3'
    stem_chs: Optional[int] = None
    group_size: Optional[int] = None
    attn_layer: Optional[str] = None
    attn_kwargs: Optional[Dict[str, Any]] = None
    attn_gain: float = 2.0  # NF correction gain when attn is used
    width_factor: float = 1.0
    bottle_ratio: float = 0.5
    num_features: int = 0
    ch_div: int = 8
    reg: bool = False  # RegNet-like: expand from in_chs, attn in middle
    extra_conv: bool = False
    gamma_in_act: bool = False
    same_padding: bool = False
    std_conv_eps: float = 1e-5
    skipinit: bool = False
    zero_init_fc: bool = False
    act_layer: str = 'silu'


def act_with_gamma(act_type: str, gamma: float = 1.0) -> Callable:
    """Gamma-scaled activation (reference nfnet.py:64-105 GammaAct)."""
    fn = get_act_fn(act_type)

    def _act(x):
        return fn(x) * gamma
    return _act


# variance-preserving gains, from the official deepmind nfnets repo
_nonlin_gamma = dict(
    identity=1.0,
    celu=1.270926833152771,
    elu=1.2716004848480225,
    gelu=1.7015043497085571,
    leaky_relu=1.70590341091156,
    log_sigmoid=1.9193484783172607,
    log_softmax=1.0002083778381348,
    relu=1.7139588594436646,
    relu6=1.7131484746932983,
    selu=1.0008515119552612,
    sigmoid=4.803835391998291,
    silu=1.7881293296813965,
    softsign=2.338853120803833,
    softplus=1.9203323125839233,
    tanh=1.5939117670059204,
)


class DownsampleAvg(nnx.Module):
    """AvgPool + std-conv shortcut (reference nfnet.py:107-151)."""

    def __init__(self, in_chs, out_chs, stride=1, dilation=1, first_dilation=None,
                 conv_layer=ScaledStdConv2d, *, dtype=None, param_dtype=jnp.float32, rngs):
        self.pool_stride = stride if dilation == 1 else 1
        self.do_pool = stride > 1 or dilation > 1
        self.conv = conv_layer(in_chs, out_chs, 1, stride=1,
                               dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        if self.do_pool:
            x = avg_pool2d(x, 2, self.pool_stride, pad_same=True)
        return self.conv(x)


class NormFreeBlock(nnx.Module):
    """Pre-activation norm-free residual block (reference nfnet.py:153-283)."""

    def __init__(self, in_chs, out_chs=None, stride=1, dilation=1, first_dilation=None,
                 alpha=1.0, beta=1.0, bottle_ratio=0.25, group_size=None, ch_div=1,
                 reg=True, extra_conv=False, skipinit=False, attn_layer=None,
                 attn_gain=2.0, act_layer=None, conv_layer=ScaledStdConv2d,
                 drop_path_rate=0., *, dtype=None, param_dtype=jnp.float32, rngs):
        first_dilation = first_dilation or dilation
        out_chs = out_chs or in_chs
        # RegNet variants scale bottleneck from in_chs, ResNet-like from out_chs
        mid_chs = make_divisible(in_chs * bottle_ratio if reg else out_chs * bottle_ratio, ch_div)
        groups = 1 if not group_size else mid_chs // group_size
        if group_size and group_size % ch_div == 0:
            mid_chs = group_size * groups
        self.alpha = alpha
        self.beta = beta
        self.attn_gain = attn_gain
        dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        if in_chs != out_chs or stride != 1 or dilation != first_dilation:
            self.downsample = DownsampleAvg(
                in_chs, out_chs, stride=stride, dilation=dilation,
                first_dilation=first_dilation, conv_layer=conv_layer, **dd)
        else:
            self.downsample = None

        self.act1 = act_layer
        self.conv1 = conv_layer(in_chs, mid_chs, 1, **dd)
        self.act2 = act_layer
        self.conv2 = conv_layer(mid_chs, mid_chs, 3, stride=stride, dilation=first_dilation,
                                groups=groups, **dd)
        if extra_conv:
            self.act2b = act_layer
            self.conv2b = conv_layer(mid_chs, mid_chs, 3, stride=1, dilation=dilation,
                                     groups=groups, **dd)
        else:
            self.act2b = None
            self.conv2b = None
        self.attn = attn_layer(mid_chs, **dd) if reg and attn_layer is not None else None
        self.act3 = act_layer
        self.conv3 = conv_layer(mid_chs, out_chs, 1, gain_init=1. if skipinit else 0., **dd)
        self.attn_last = attn_layer(out_chs, **dd) if not reg and attn_layer is not None else None
        self.drop_path = DropPath(drop_path_rate, rngs=rngs)
        self.skipinit_gain = nnx.Param(jnp.zeros((), param_dtype)) if skipinit else None

    def __call__(self, x):
        out = self.act1(x) * self.beta
        shortcut = x
        if self.downsample is not None:
            shortcut = self.downsample(out)
        out = self.conv1(out)
        out = self.conv2(self.act2(out))
        if self.conv2b is not None:
            out = self.conv2b(self.act2b(out))
        if self.attn is not None:
            out = self.attn_gain * self.attn(out)
        out = self.conv3(self.act3(out))
        if self.attn_last is not None:
            out = self.attn_gain * self.attn_last(out)
        out = self.drop_path(out)
        if self.skipinit_gain is not None:
            out = out * self.skipinit_gain[...].astype(out.dtype)
        return out * self.alpha + shortcut


class Stem(nnx.Module):
    """Norm-free stem (reference nfnet.py:285-347 create_stem)."""

    def __init__(self, in_chs, out_chs, stem_type='', conv_layer=None, act_layer=None,
                 *, dtype=None, param_dtype=jnp.float32, rngs):
        assert stem_type in ('', 'deep', 'deep_tiered', 'deep_quad', '3x3', '7x7',
                             'deep_pool', '3x3_pool', '7x7_pool')
        dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.stride = 2
        self.act = act_layer
        self.feature = dict(num_chs=out_chs, reduction=2, module='stem.conv')
        self.conv_names = []
        if 'deep' in stem_type:
            if 'quad' in stem_type:
                assert 'pool' not in stem_type
                stem_chs = (out_chs // 8, out_chs // 4, out_chs // 2, out_chs)
                strides = (2, 1, 1, 2)
                self.stride = 4
                self.feature = dict(num_chs=out_chs // 2, reduction=2, module='stem.conv3')
            else:
                if 'tiered' in stem_type:
                    stem_chs = (3 * out_chs // 8, out_chs // 2, out_chs)
                else:
                    stem_chs = (out_chs // 2, out_chs // 2, out_chs)
                strides = (2, 1, 1)
                self.feature = dict(num_chs=out_chs // 2, reduction=2, module='stem.conv2')
            prev = in_chs
            for i, (c, s) in enumerate(zip(stem_chs, strides)):
                setattr(self, f'conv{i + 1}', conv_layer(prev, c, kernel_size=3, stride=s, **dd))
                self.conv_names.append(f'conv{i + 1}')
                prev = c
            self.last_act = False  # act applied between convs, not after last
        elif '3x3' in stem_type:
            self.conv = conv_layer(in_chs, out_chs, kernel_size=3, stride=2, **dd)
            self.conv_names = ['conv']
            self.last_act = False
        else:  # 7x7
            self.conv = conv_layer(in_chs, out_chs, kernel_size=7, stride=2, **dd)
            self.conv_names = ['conv']
            self.last_act = False
        self.pool = 'pool' in stem_type
        if self.pool:
            self.stride = 4

    def __call__(self, x):
        for i, name in enumerate(self.conv_names):
            x = getattr(self, name)(x)
            if i != len(self.conv_names) - 1:
                x = self.act(x)
        if self.pool:
            x = max_pool2d(x, 3, 2)
        return x


class NormFreeNet(nnx.Module):
    """Normalization-free network (reference nfnet.py:368-596)."""

    def __init__(
            self,
            cfg: NfCfg,
            num_classes: int = 1000,
            in_chans: int = 3,
            global_pool: str = 'avg',
            output_stride: int = 32,
            drop_rate: float = 0.,
            drop_path_rate: float = 0.,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: Optional[nnx.Rngs] = None,
            **kwargs,
    ):
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        cfg = replace(cfg, **kwargs)
        self.num_classes = num_classes
        self.drop_rate = drop_rate
        self.grad_checkpointing = False
        dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        assert cfg.act_layer in _nonlin_gamma, \
            f'Please add non-linearity constants for activation ({cfg.act_layer}).'
        conv_layer = ScaledStdConv2dSame if cfg.same_padding else ScaledStdConv2d
        if cfg.gamma_in_act:
            act_layer = act_with_gamma(cfg.act_layer, gamma=_nonlin_gamma[cfg.act_layer])
            conv_layer = partial(conv_layer, eps=cfg.std_conv_eps)
        else:
            act_layer = get_act_fn(cfg.act_layer)
            conv_layer = partial(conv_layer, gamma=_nonlin_gamma[cfg.act_layer], eps=cfg.std_conv_eps)
        attn_layer = partial(get_attn(cfg.attn_layer), **(cfg.attn_kwargs or {})) \
            if cfg.attn_layer else None

        stem_chs = make_divisible((cfg.stem_chs or cfg.channels[0]) * cfg.width_factor, cfg.ch_div)
        self.stem = Stem(in_chans, stem_chs, cfg.stem_type, conv_layer=conv_layer,
                         act_layer=act_layer, **dd)
        stem_stride = self.stem.stride

        self.feature_info = [self.stem.feature]
        drop_path_rates = calculate_drop_path_rates(drop_path_rate, cfg.depths, stagewise=True)
        prev_chs = stem_chs
        net_stride = stem_stride
        dilation = 1
        expected_var = 1.0
        stages = []
        for stage_idx, stage_depth in enumerate(cfg.depths):
            stride = 1 if stage_idx == 0 and stem_stride > 2 else 2
            if net_stride >= output_stride and stride > 1:
                dilation *= stride
                stride = 1
            net_stride *= stride
            first_dilation = 1 if dilation in (1, 2) else 2

            blocks = []
            for block_idx in range(stage_depth):
                first_block = block_idx == 0 and stage_idx == 0
                out_chs = make_divisible(cfg.channels[stage_idx] * cfg.width_factor, cfg.ch_div)
                blocks += [NormFreeBlock(
                    in_chs=prev_chs, out_chs=out_chs,
                    alpha=cfg.alpha,
                    beta=1. / expected_var ** 0.5,
                    stride=stride if block_idx == 0 else 1,
                    dilation=dilation,
                    first_dilation=first_dilation,
                    group_size=cfg.group_size,
                    bottle_ratio=1. if cfg.reg and first_block else cfg.bottle_ratio,
                    ch_div=cfg.ch_div,
                    reg=cfg.reg,
                    extra_conv=cfg.extra_conv,
                    skipinit=cfg.skipinit,
                    attn_layer=attn_layer,
                    attn_gain=cfg.attn_gain,
                    act_layer=act_layer,
                    conv_layer=conv_layer,
                    drop_path_rate=drop_path_rates[stage_idx][block_idx],
                    **dd,
                )]
                if block_idx == 0:
                    expected_var = 1.0  # reset after first block of each stage
                expected_var += cfg.alpha ** 2
                first_dilation = dilation
                prev_chs = out_chs
            self.feature_info += [dict(num_chs=prev_chs, reduction=net_stride, module=f'stages.{stage_idx}')]
            stages += [nnx.List(blocks)]
        self.stages = nnx.List(stages)

        if cfg.num_features:
            self.num_features = make_divisible(cfg.width_factor * cfg.num_features, cfg.ch_div)
            self.final_conv = conv_layer(prev_chs, self.num_features, 1, **dd)
            self.feature_info[-1] = dict(
                num_chs=self.num_features, reduction=net_stride, module='final_conv')
        else:
            self.num_features = prev_chs
            self.final_conv = None
        self.final_act = act_layer

        self.head_hidden_size = self.num_features
        self.head = ClassifierHead(
            self.num_features, num_classes, pool_type=global_pool, drop_rate=drop_rate, **dd)
        if cfg.zero_init_fc and self.head.fc is not None:
            self.head.fc.kernel[...] = jnp.zeros_like(self.head.fc.kernel[...])

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return set()

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^stem',
            blocks=[
                (r'^stages\.(\d+)' if coarse else r'^stages\.(\d+)\.(\d+)', None),
                (r'^final_conv', (99999,)),
            ],
        )

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def get_classifier(self):
        return self.head.fc

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        self.head.reset(num_classes, global_pool, rngs=rngs)

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        x = self.stem(x)
        for stage in self.stages:
            if self.grad_checkpointing:
                x = checkpoint_seq(stage, x)
            else:
                for b in stage:
                    x = b(x)
        if self.final_conv is not None:
            x = self.final_conv(x)
        return self.final_act(x)

    def forward_head(self, x, pre_logits: bool = False):
        return self.head(x, pre_logits=pre_logits)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(self, x, indices=None, norm: bool = False,
                              stop_early: bool = False, output_fmt: str = 'NHWC',
                              intermediates_only: bool = False):
        assert output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.stages) + 1, indices)
        intermediates = []
        x = self.stem(x)
        if 0 in take_indices:
            intermediates.append(x)
        for i, stage in enumerate(self.stages):
            if not stop_early or i <= max_index - 1:
                for b in stage:
                    x = b(x)
                if (i + 1) in take_indices:
                    intermediates.append(x)
        if intermediates_only:
            return intermediates
        if self.final_conv is not None:
            x = self.final_conv(x)
        x = self.final_act(x)
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, _ = feature_take_indices(len(self.stages) + 1, indices)
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def _nfres_cfg(depths, channels=(256, 512, 1024, 2048), group_size=None,
               act_layer='relu', attn_layer=None, attn_kwargs=None) -> NfCfg:
    return NfCfg(
        depths=depths, channels=channels, stem_type='7x7_pool', stem_chs=64,
        bottle_ratio=0.25, group_size=group_size, act_layer=act_layer,
        attn_layer=attn_layer, attn_kwargs=attn_kwargs or {})


def _nfreg_cfg(depths, channels=(48, 104, 208, 440)) -> NfCfg:
    return NfCfg(
        depths=depths, channels=channels, stem_type='3x3', group_size=8,
        width_factor=0.75, bottle_ratio=2.25, num_features=1280 * channels[-1] // 440,
        reg=True, attn_layer='se', attn_kwargs=dict(rd_ratio=0.5))


def _nfnet_cfg(depths, channels=(256, 512, 1536, 1536), group_size=128, bottle_ratio=0.5,
               feat_mult=2., act_layer='gelu', attn_layer='se', attn_kwargs=None) -> NfCfg:
    return NfCfg(
        depths=depths, channels=channels, stem_type='deep_quad', stem_chs=128,
        group_size=group_size, bottle_ratio=bottle_ratio, extra_conv=True,
        num_features=int(channels[-1] * feat_mult), act_layer=act_layer,
        attn_layer=attn_layer,
        attn_kwargs=attn_kwargs if attn_kwargs is not None else dict(rd_ratio=0.5))


def _dm_nfnet_cfg(depths, channels=(256, 512, 1536, 1536), act_layer='gelu',
                  skipinit=True) -> NfCfg:
    return NfCfg(
        depths=depths, channels=channels, stem_type='deep_quad', stem_chs=128,
        group_size=128, bottle_ratio=0.5, extra_conv=True, gamma_in_act=True,
        same_padding=True, skipinit=skipinit, num_features=int(channels[-1] * 2.0),
        act_layer=act_layer, attn_layer='se', attn_kwargs=dict(rd_ratio=0.5))


model_cfgs = dict(
    dm_nfnet_f0=_dm_nfnet_cfg(depths=(1, 2, 6, 3)),
    dm_nfnet_f1=_dm_nfnet_cfg(depths=(2, 4, 12, 6)),
    dm_nfnet_f2=_dm_nfnet_cfg(depths=(3, 6, 18, 9)),
    dm_nfnet_f3=_dm_nfnet_cfg(depths=(4, 8, 24, 12)),
    dm_nfnet_f4=_dm_nfnet_cfg(depths=(5, 10, 30, 15)),
    dm_nfnet_f5=_dm_nfnet_cfg(depths=(6, 12, 36, 18)),
    dm_nfnet_f6=_dm_nfnet_cfg(depths=(7, 14, 42, 21)),

    nfnet_f0=_nfnet_cfg(depths=(1, 2, 6, 3)),
    nfnet_f1=_nfnet_cfg(depths=(2, 4, 12, 6)),
    nfnet_f2=_nfnet_cfg(depths=(3, 6, 18, 9)),
    nfnet_f3=_nfnet_cfg(depths=(4, 8, 24, 12)),
    nfnet_f4=_nfnet_cfg(depths=(5, 10, 30, 15)),
    nfnet_f5=_nfnet_cfg(depths=(6, 12, 36, 18)),
    nfnet_f6=_nfnet_cfg(depths=(7, 14, 42, 21)),
    nfnet_f7=_nfnet_cfg(depths=(8, 16, 48, 24)),

    nfnet_l0=_nfnet_cfg(
        depths=(1, 2, 6, 3), feat_mult=1.5, group_size=64, bottle_ratio=0.25,
        attn_kwargs=dict(rd_ratio=0.25, rd_divisor=8), act_layer='silu'),
    eca_nfnet_l0=_nfnet_cfg(
        depths=(1, 2, 6, 3), feat_mult=1.5, group_size=64, bottle_ratio=0.25,
        attn_layer='eca', attn_kwargs=dict(), act_layer='silu'),
    eca_nfnet_l1=_nfnet_cfg(
        depths=(2, 4, 12, 6), feat_mult=2, group_size=64, bottle_ratio=0.25,
        attn_layer='eca', attn_kwargs=dict(), act_layer='silu'),
    eca_nfnet_l2=_nfnet_cfg(
        depths=(3, 6, 18, 9), feat_mult=2, group_size=64, bottle_ratio=0.25,
        attn_layer='eca', attn_kwargs=dict(), act_layer='silu'),
    eca_nfnet_l3=_nfnet_cfg(
        depths=(4, 8, 24, 12), feat_mult=2, group_size=64, bottle_ratio=0.25,
        attn_layer='eca', attn_kwargs=dict(), act_layer='silu'),

    nf_regnet_b0=_nfreg_cfg(depths=(1, 3, 6, 6)),
    nf_regnet_b1=_nfreg_cfg(depths=(2, 4, 7, 7)),
    nf_regnet_b2=_nfreg_cfg(depths=(2, 4, 8, 8), channels=(56, 112, 232, 488)),
    nf_regnet_b3=_nfreg_cfg(depths=(2, 5, 9, 9), channels=(56, 128, 248, 528)),
    nf_regnet_b4=_nfreg_cfg(depths=(2, 6, 11, 11), channels=(64, 144, 288, 616)),
    nf_regnet_b5=_nfreg_cfg(depths=(3, 7, 14, 14), channels=(80, 168, 336, 704)),

    nf_resnet26=_nfres_cfg(depths=(2, 2, 2, 2)),
    nf_resnet50=_nfres_cfg(depths=(3, 4, 6, 3)),
    nf_resnet101=_nfres_cfg(depths=(3, 4, 23, 3)),

    nf_seresnet26=_nfres_cfg(depths=(2, 2, 2, 2), attn_layer='se', attn_kwargs=dict(rd_ratio=1 / 16)),
    nf_seresnet50=_nfres_cfg(depths=(3, 4, 6, 3), attn_layer='se', attn_kwargs=dict(rd_ratio=1 / 16)),
    nf_seresnet101=_nfres_cfg(depths=(3, 4, 23, 3), attn_layer='se', attn_kwargs=dict(rd_ratio=1 / 16)),

    nf_ecaresnet26=_nfres_cfg(depths=(2, 2, 2, 2), attn_layer='eca', attn_kwargs=dict()),
    nf_ecaresnet50=_nfres_cfg(depths=(3, 4, 6, 3), attn_layer='eca', attn_kwargs=dict()),
    nf_ecaresnet101=_nfres_cfg(depths=(3, 4, 23, 3), attn_layer='eca', attn_kwargs=dict()),

    test_nfnet=_nfnet_cfg(
        depths=(1, 1, 1, 1), channels=(32, 64, 96, 128), feat_mult=1.5, group_size=8,
        bottle_ratio=0.25, attn_kwargs=dict(rd_ratio=0.25, rd_divisor=8), act_layer='silu'),
)


def checkpoint_filter_fn(state_dict, model):
    """Reference nfnet layouts map 1:1; the ScaledStdConv gain is stored
    (C, 1, 1, 1) in torch and (C,) here."""
    from ._torch_convert import convert_torch_state_dict
    out = {}
    for k, v in state_dict.items():
        if k.endswith('.gain') and getattr(v, 'ndim', 0) == 4:
            v = v.reshape(v.shape[0])
        out[k] = v
    return convert_torch_state_dict(out, model)


def _create_normfreenet(variant: str, pretrained: bool = False, **kwargs) -> NormFreeNet:
    return build_model_with_cfg(
        NormFreeNet, variant, pretrained,
        model_cfg=model_cfgs[variant],
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(flatten_sequential=True),
        **kwargs,
    )


def _dcfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000,
        'input_size': (3, 224, 224),
        'pool_size': (7, 7),
        'crop_pct': 0.9,
        'interpolation': 'bicubic',
        'mean': (0.485, 0.456, 0.406),
        'std': (0.229, 0.224, 0.225),
        'first_conv': 'stem.conv1',
        'classifier': 'head.fc',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'dm_nfnet_f0.dm_in1k': _dcfg(input_size=(3, 192, 192), pool_size=(6, 6), test_input_size=(3, 256, 256)),
    'dm_nfnet_f1.dm_in1k': _dcfg(input_size=(3, 224, 224), test_input_size=(3, 320, 320)),
    'dm_nfnet_f2.dm_in1k': _dcfg(input_size=(3, 256, 256), pool_size=(8, 8), test_input_size=(3, 352, 352)),
    'dm_nfnet_f3.dm_in1k': _dcfg(input_size=(3, 320, 320), pool_size=(10, 10), test_input_size=(3, 416, 416)),
    'dm_nfnet_f4.dm_in1k': _dcfg(input_size=(3, 384, 384), pool_size=(12, 12), test_input_size=(3, 512, 512)),
    'dm_nfnet_f5.dm_in1k': _dcfg(input_size=(3, 416, 416), pool_size=(13, 13), test_input_size=(3, 544, 544)),
    'dm_nfnet_f6.dm_in1k': _dcfg(input_size=(3, 448, 448), pool_size=(14, 14), test_input_size=(3, 576, 576)),
    'nfnet_f0.untrained': _dcfg(input_size=(3, 192, 192), pool_size=(6, 6)),
    'nfnet_f1.untrained': _dcfg(),
    'nfnet_f2.untrained': _dcfg(input_size=(3, 256, 256), pool_size=(8, 8)),
    'nfnet_f3.untrained': _dcfg(input_size=(3, 320, 320), pool_size=(10, 10)),
    'nfnet_f4.untrained': _dcfg(input_size=(3, 384, 384), pool_size=(12, 12)),
    'nfnet_f5.untrained': _dcfg(input_size=(3, 416, 416), pool_size=(13, 13)),
    'nfnet_f6.untrained': _dcfg(input_size=(3, 448, 448), pool_size=(14, 14)),
    'nfnet_f7.untrained': _dcfg(input_size=(3, 480, 480), pool_size=(15, 15)),
    'nfnet_l0.ra2_in1k': _dcfg(input_size=(3, 224, 224), test_input_size=(3, 288, 288), crop_pct=1.0),
    'eca_nfnet_l0.ra2_in1k': _dcfg(input_size=(3, 224, 224), test_input_size=(3, 288, 288), crop_pct=1.0),
    'eca_nfnet_l1.ra2_in1k': _dcfg(input_size=(3, 256, 256), pool_size=(8, 8), test_input_size=(3, 320, 320), crop_pct=1.0),
    'eca_nfnet_l2.ra3_in1k': _dcfg(input_size=(3, 320, 320), pool_size=(10, 10), test_input_size=(3, 384, 384), crop_pct=1.0),
    'eca_nfnet_l3.untrained': _dcfg(input_size=(3, 352, 352), pool_size=(11, 11), test_input_size=(3, 448, 448), crop_pct=1.0),
    'nf_regnet_b0.untrained': _dcfg(first_conv='stem.conv'),
    'nf_regnet_b1.ra2_in1k': _dcfg(first_conv='stem.conv', input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=0.9),
    'nf_regnet_b2.untrained': _dcfg(first_conv='stem.conv'),
    'nf_regnet_b3.untrained': _dcfg(first_conv='stem.conv'),
    'nf_regnet_b4.untrained': _dcfg(first_conv='stem.conv'),
    'nf_regnet_b5.untrained': _dcfg(first_conv='stem.conv'),
    'nf_resnet26.untrained': _dcfg(first_conv='stem.conv'),
    'nf_resnet50.ra2_in1k': _dcfg(first_conv='stem.conv', input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=0.94),
    'nf_resnet101.untrained': _dcfg(first_conv='stem.conv'),
    'nf_seresnet26.untrained': _dcfg(first_conv='stem.conv'),
    'nf_seresnet50.untrained': _dcfg(first_conv='stem.conv'),
    'nf_seresnet101.untrained': _dcfg(first_conv='stem.conv'),
    'nf_ecaresnet26.untrained': _dcfg(first_conv='stem.conv'),
    'nf_ecaresnet50.untrained': _dcfg(first_conv='stem.conv'),
    'nf_ecaresnet101.untrained': _dcfg(first_conv='stem.conv'),
    'test_nfnet.r160_in1k': _dcfg(input_size=(3, 160, 160), pool_size=(5, 5), crop_pct=0.95),
})


@register_model
def dm_nfnet_f0(pretrained=False, **kwargs) -> NormFreeNet:
    """NFNet-F0 w/ DeepMind weight compatibility (SAME padding, gamma-in-act)."""
    return _create_normfreenet('dm_nfnet_f0', pretrained=pretrained, **kwargs)


@register_model
def dm_nfnet_f1(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('dm_nfnet_f1', pretrained=pretrained, **kwargs)


@register_model
def dm_nfnet_f2(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('dm_nfnet_f2', pretrained=pretrained, **kwargs)


@register_model
def dm_nfnet_f3(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('dm_nfnet_f3', pretrained=pretrained, **kwargs)


@register_model
def dm_nfnet_f4(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('dm_nfnet_f4', pretrained=pretrained, **kwargs)


@register_model
def dm_nfnet_f5(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('dm_nfnet_f5', pretrained=pretrained, **kwargs)


@register_model
def dm_nfnet_f6(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('dm_nfnet_f6', pretrained=pretrained, **kwargs)


@register_model
def nfnet_f0(pretrained=False, **kwargs) -> NormFreeNet:
    """NFNet-F0 (https://arxiv.org/abs/2102.06171)."""
    return _create_normfreenet('nfnet_f0', pretrained=pretrained, **kwargs)


@register_model
def nfnet_f1(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('nfnet_f1', pretrained=pretrained, **kwargs)


@register_model
def nfnet_f2(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('nfnet_f2', pretrained=pretrained, **kwargs)


@register_model
def nfnet_f3(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('nfnet_f3', pretrained=pretrained, **kwargs)


@register_model
def nfnet_f4(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('nfnet_f4', pretrained=pretrained, **kwargs)


@register_model
def nfnet_f5(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('nfnet_f5', pretrained=pretrained, **kwargs)


@register_model
def nfnet_f6(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('nfnet_f6', pretrained=pretrained, **kwargs)


@register_model
def nfnet_f7(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('nfnet_f7', pretrained=pretrained, **kwargs)


@register_model
def nfnet_l0(pretrained=False, **kwargs) -> NormFreeNet:
    """NFNet-L0: F0 body with SE rd_ratio 0.25 and SiLU."""
    return _create_normfreenet('nfnet_l0', pretrained=pretrained, **kwargs)


@register_model
def eca_nfnet_l0(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('eca_nfnet_l0', pretrained=pretrained, **kwargs)


@register_model
def eca_nfnet_l1(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('eca_nfnet_l1', pretrained=pretrained, **kwargs)


@register_model
def eca_nfnet_l2(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('eca_nfnet_l2', pretrained=pretrained, **kwargs)


@register_model
def eca_nfnet_l3(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('eca_nfnet_l3', pretrained=pretrained, **kwargs)


@register_model
def nf_regnet_b0(pretrained=False, **kwargs) -> NormFreeNet:
    """Norm-free RegNet-B0 (https://arxiv.org/abs/2101.08692)."""
    return _create_normfreenet('nf_regnet_b0', pretrained=pretrained, **kwargs)


@register_model
def nf_regnet_b1(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('nf_regnet_b1', pretrained=pretrained, **kwargs)


@register_model
def nf_regnet_b2(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('nf_regnet_b2', pretrained=pretrained, **kwargs)


@register_model
def nf_regnet_b3(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('nf_regnet_b3', pretrained=pretrained, **kwargs)


@register_model
def nf_regnet_b4(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('nf_regnet_b4', pretrained=pretrained, **kwargs)


@register_model
def nf_regnet_b5(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('nf_regnet_b5', pretrained=pretrained, **kwargs)


@register_model
def nf_resnet26(pretrained=False, **kwargs) -> NormFreeNet:
    """Norm-free pre-activation ResNet-26."""
    return _create_normfreenet('nf_resnet26', pretrained=pretrained, **kwargs)


@register_model
def nf_resnet50(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('nf_resnet50', pretrained=pretrained, **kwargs)


@register_model
def nf_resnet101(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('nf_resnet101', pretrained=pretrained, **kwargs)


@register_model
def nf_seresnet26(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('nf_seresnet26', pretrained=pretrained, **kwargs)


@register_model
def nf_seresnet50(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('nf_seresnet50', pretrained=pretrained, **kwargs)


@register_model
def nf_seresnet101(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('nf_seresnet101', pretrained=pretrained, **kwargs)


@register_model
def nf_ecaresnet26(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('nf_ecaresnet26', pretrained=pretrained, **kwargs)


@register_model
def nf_ecaresnet50(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('nf_ecaresnet50', pretrained=pretrained, **kwargs)


@register_model
def nf_ecaresnet101(pretrained=False, **kwargs) -> NormFreeNet:
    return _create_normfreenet('nf_ecaresnet101', pretrained=pretrained, **kwargs)


@register_model
def test_nfnet(pretrained=False, **kwargs) -> NormFreeNet:
    """Minimal NFNet for testing."""
    return _create_normfreenet('test_nfnet', pretrained=pretrained, **kwargs)
