#!/usr/bin/env python3
"""Folder inference → top-k predictions to csv/json/parquet
(reference: inference.py:1-389).
"""
from __future__ import annotations

import argparse
import json
import logging
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

_logger = logging.getLogger('inference')

parser = argparse.ArgumentParser(description='TPU-native inference')
parser.add_argument('data', nargs='?', metavar='DIR', const=None)
parser.add_argument('--data-dir', metavar='DIR')
parser.add_argument('--dataset', metavar='NAME', default='')
parser.add_argument('--split', metavar='NAME', default='validation')
parser.add_argument('--model', '-m', metavar='NAME', default='vit_tiny_patch16_224')
parser.add_argument('--pretrained', action='store_true')
parser.add_argument('--checkpoint', default='', type=str, metavar='PATH')
parser.add_argument('--use-ema', action='store_true')
parser.add_argument('-b', '--batch-size', default=256, type=int)
parser.add_argument('--img-size', default=None, type=int)
parser.add_argument('--input-size', default=None, nargs=3, type=int)
parser.add_argument('--crop-pct', default=None, type=float)
parser.add_argument('--crop-mode', default=None, type=str)
parser.add_argument('--num-classes', type=int, default=None)
parser.add_argument('--class-map', default='', type=str)
parser.add_argument('--label-type', default='index', type=str,
                    choices=['index', 'name', 'description', 'detail'],
                    help="'name'/'description' resolve ImageNet synsets/lemmas from bundled "
                         'class metadata (falling back to dataset class-folder names)')
parser.add_argument('-j', '--workers', default=4, type=int)
parser.add_argument('--amp', action='store_true', default=False)
parser.add_argument('--device', default=None, type=str,
                    help="jax platform override (e.g. 'cpu'); must be set before first device op")
parser.add_argument('--topk', default=1, type=int, metavar='N')
parser.add_argument('--fullname', action='store_true', default=False)
parser.add_argument('--outputs-name', default=None)
parser.add_argument('--output-dir', default=None)
parser.add_argument('--output-type', default='csv', choices=['csv', 'json', 'parquet'])
parser.add_argument('--filename-col', default='filename')
parser.add_argument('--block-scan', action='store_true', default=False,
                    help='scan-over-layers block execution (O(1)-in-depth trace/compile)')
parser.add_argument('--device-prefetch', type=int, default=0, metavar='N',
                    help='keep N batches in flight on device while the step runs; 0 disables')


def main():
    import timm_tpu
    from timm_tpu.data import create_dataset, create_loader, resolve_data_config
    from timm_tpu.models import load_checkpoint
    from timm_tpu.utils import setup_default_logging
    from flax import nnx

    setup_default_logging()
    args = parser.parse_args()

    if args.device:
        # must land before the first device op (model init); env JAX_PLATFORMS
        # loses to the axon plugin's sitecustomize registration
        jax.config.update('jax_platforms', args.device)
    from timm_tpu.utils import configure_compile_cache
    configure_compile_cache()
    dtype = jnp.bfloat16 if args.amp else None
    try:
        model = timm_tpu.create_model(
            args.model, pretrained=args.pretrained, num_classes=args.num_classes,
            img_size=args.img_size, dtype=dtype)
    except TypeError:
        model = timm_tpu.create_model(
            args.model, pretrained=args.pretrained, num_classes=args.num_classes, dtype=dtype)
    if args.checkpoint:
        load_checkpoint(model, args.checkpoint, use_ema=args.use_ema)
    if args.block_scan:
        if hasattr(model, 'set_block_scan'):
            model.set_block_scan(True)
        else:
            _logger.warning(f'--block-scan: {args.model} has no scannable block stack; ignored')
    model.eval()

    data_config = resolve_data_config(vars(args), model=model)
    root = args.data_dir or args.data
    dataset = create_dataset(args.dataset, root=root, split=args.split, class_map=args.class_map)
    loader = create_loader(
        dataset,
        input_size=data_config['input_size'],
        batch_size=args.batch_size,
        interpolation=data_config['interpolation'],
        mean=data_config['mean'],
        std=data_config['std'],
        num_workers=args.workers,
        crop_pct=data_config['crop_pct'],
        crop_mode=data_config['crop_mode'],
        device_prefetch=args.device_prefetch,
    )

    graphdef, state = nnx.split(model)
    mean = jnp.asarray(data_config['mean'], jnp.float32).reshape(1, 1, 1, -1)
    std = jnp.asarray(data_config['std'], jnp.float32).reshape(1, 1, 1, -1)
    k = min(args.topk, args.num_classes or model.num_classes)

    @jax.jit
    def infer_step(state, x):
        x = (x - mean) / std
        if dtype is not None:
            x = x.astype(dtype)
        logits = nnx.merge(graphdef, state)(x).astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)
        order = jnp.argsort(probs, axis=-1)[:, ::-1][:, :k]
        top_probs = jnp.take_along_axis(probs, order, axis=-1)
        return order, top_probs

    # every batch — including the final partial one — runs at one padded
    # bucket shape, so the whole loop uses a single compiled executable
    # instead of paying a fresh XLA compile for the odd-sized last batch
    from timm_tpu.serve import batch_bucket, pad_rows, strip_rows
    bucket = batch_bucket(args.batch_size)

    all_indices, all_probs = [], []
    t0 = time.time()
    for x_np, _ in loader:
        n = int(x_np.shape[0])
        if n != bucket:  # partial final batch: pad up to the bucket shape
            x_np, _valid = pad_rows(np.asarray(x_np), bucket)
        idx, prb = strip_rows(infer_step(state, jnp.asarray(x_np)), n)
        all_indices.append(np.asarray(idx))
        all_probs.append(np.asarray(prb))
    if not all_indices:
        raise RuntimeError(f'No images found for inference under {root!r} (split {args.split!r})')
    num = sum(a.shape[0] for a in all_indices)
    _logger.info(f'Inference complete: {num} images in {time.time() - t0:.1f}s')

    indices = np.concatenate(all_indices)
    probs = np.concatenate(all_probs)
    filenames = dataset.filenames(basename=not args.fullname)[:num]

    to_label = None
    if args.label_type in ('name', 'description', 'detail'):
        # prefer the model's ImageNet label space (reference inference.py:213)
        from timm_tpu.data.dataset_info import ImageNetInfo, infer_imagenet_subset
        subset = infer_imagenet_subset({'num_classes': args.num_classes or model.num_classes})
        if subset is not None:
            info = ImageNetInfo(subset)
            if args.label_type == 'name':
                to_label = info.index_to_label_name
            else:
                from functools import partial
                to_label = partial(info.index_to_description, detailed=args.label_type == 'detail')
        elif hasattr(dataset, 'reader') and hasattr(dataset.reader, 'class_to_idx'):
            idx_to_name = {v: k for k, v in dataset.reader.class_to_idx.items()}
            to_label = lambda i: idx_to_name.get(i, i)

    def _label(i: int):
        return to_label(int(i)) if to_label is not None else int(i)

    rows = []
    for fn, ind, prb in zip(filenames, indices, probs):
        row = {args.filename_col: fn}
        if k == 1:
            row['label'] = _label(int(ind[0]))
            row['prob'] = float(prb[0])
        else:
            for j in range(k):
                row[f'label_{j}'] = _label(int(ind[j]))
                row[f'prob_{j}'] = float(prb[j])
        rows.append(row)

    out_dir = args.output_dir or '.'
    os.makedirs(out_dir, exist_ok=True)
    base = os.path.join(out_dir, args.outputs_name or f'{args.model}-results')
    if args.output_type == 'json':
        with open(base + '.json', 'w') as f:  # timm-tpu-lint: disable=process-zero-io single-process inference driver; no pod launch path
            json.dump(rows, f, indent=2)
    elif args.output_type == 'parquet':
        import pandas as pd
        pd.DataFrame(rows).set_index(args.filename_col).to_parquet(base + '.parquet')
    else:
        import csv
        with open(base + '.csv', 'w') as f:  # timm-tpu-lint: disable=process-zero-io single-process inference driver; no pod launch path
            dw = csv.DictWriter(f, fieldnames=rows[0].keys())
            dw.writeheader()
            for r in rows:
                dw.writerow(r)
    _logger.info(f'Wrote results to {base}.{args.output_type}')


if __name__ == '__main__':
    main()
