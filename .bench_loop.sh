#!/bin/bash
# Round-5 bench self-measurement loop: keep trying until the TPU answers,
# then refresh the self-measured result every ~45 min. The self loop can
# afford a much larger wall-clock budget than the driver's run.
cd /root/repo
while true; do
  BENCH_TOTAL_BUDGET=1800 python bench.py --save-self >> /tmp/bench_loop.log 2>&1
  rc=$?
  echo "[$(date -u +%FT%TZ)] bench.py --save-self rc=$rc" >> /tmp/bench_loop.log
  if [ $rc -eq 0 ]; then
    sleep 2700
  else
    sleep 180
  fi
done
