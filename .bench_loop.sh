#!/bin/bash
# Round-5 bench self-measurement loop: keep trying until the TPU answers,
# then refresh the self-measured result every ~45 min. The self loop can
# afford a much larger wall-clock budget than the driver's run.
#
# BENCH_SELF.json is the v2 document (timm_tpu/perfbudget/replay.py): failed
# rounds append structured abort records (bounded history) instead of leaving
# an empty file, and the replay below streams its per-step results into the
# same document.
cd /root/repo
while true; do
  # static-analysis gate: never measure a repo the analyzers reject. Full
  # suite (source + jaxpr + HLO rules + zoo abstract-trace); the report lands
  # in ANALYSIS_SELF.json so a failed gate leaves evidence next to the bench
  # doc. Exit 2 = violations, 3 = analyzer error — both skip the round.
  python -m timm_tpu.analysis --json ANALYSIS_SELF.json >> /tmp/bench_loop.log 2>&1
  arc=$?
  echo "[$(date -u +%FT%TZ)] timm_tpu.analysis rc=$arc" >> /tmp/bench_loop.log
  if [ $arc -ne 0 ]; then
    sleep 180
    continue
  fi
  BENCH_TOTAL_BUDGET=1800 python bench.py --save-self >> /tmp/bench_loop.log 2>&1
  rc=$?
  echo "[$(date -u +%FT%TZ)] bench.py --save-self rc=$rc" >> /tmp/bench_loop.log
  if [ $rc -eq 0 ]; then
    # first healthy window: run the whole queued PERF.md A/B checklist once
    # (donation, pad-tokens, bf16 knobs, fsdp x tp grid, flash gate, profiler
    # trace, serve drill) — results land in BENCH_SELF.json step by step
    if [ ! -f /tmp/bench_replay_done ]; then
      BENCH_TOTAL_BUDGET=5400 python bench.py --replay --save-self >> /tmp/bench_loop.log 2>&1
      echo "[$(date -u +%FT%TZ)] bench.py --replay rc=$? (one-shot)" >> /tmp/bench_loop.log
      touch /tmp/bench_replay_done
    elif [ ! -f /tmp/bench_autotune_done ]; then
      # autotune top-K live verification: time the solver's predicted top
      # configs on real hardware and persist the fitted correction factor
      # into BENCH_SELF.json (autotune.load_correction reads it from there).
      # One-shot like the full replay, but queued separately so loops that
      # already replayed before this step existed still verify it.
      BENCH_TOTAL_BUDGET=3600 python bench.py --replay --replay-steps autotune --save-self >> /tmp/bench_loop.log 2>&1
      echo "[$(date -u +%FT%TZ)] bench.py --replay-steps autotune rc=$? (one-shot)" >> /tmp/bench_loop.log
      touch /tmp/bench_autotune_done
    elif [ ! -f /tmp/bench_family_sweep_done ]; then
      # family coverage sweep: re-derive tests/fixtures/coverage_matrix.json
      # live (every deep-eligible family through the sharded donated step,
      # serve AOT buckets and device prefetch) and fail the step on drift
      BENCH_TOTAL_BUDGET=3600 python bench.py --replay --replay-steps family_sweep --save-self >> /tmp/bench_loop.log 2>&1
      echo "[$(date -u +%FT%TZ)] bench.py --replay-steps family_sweep rc=$? (one-shot)" >> /tmp/bench_loop.log
      touch /tmp/bench_family_sweep_done
    fi
    sleep 2700
  else
    sleep 180
  fi
done
