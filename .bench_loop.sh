#!/bin/bash
# Round-4 bench self-measurement loop: keep trying until the TPU answers,
# then refresh the self-measured result every ~45 min.
cd /root/repo
while true; do
  python bench.py --save-self >> /tmp/bench_loop.log 2>&1
  rc=$?
  echo "[$(date -u +%FT%TZ)] bench.py --save-self rc=$rc" >> /tmp/bench_loop.log
  if [ $rc -eq 0 ]; then
    sleep 2700
  else
    sleep 300
  fi
done
